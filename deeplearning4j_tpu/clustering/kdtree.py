"""KD-tree for nearest-neighbor queries.

Parity: reference core/clustering/kdtree/KDTree.java (368 LoC): insert,
nearest-neighbor, k-NN, range query. Host-side numpy (see package
docstring for why trees stay off-device).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("point", "index", "left", "right")

    def __init__(self, point: np.ndarray, index: int):
        self.point = point
        self.index = index
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    def __init__(self, dims: int):
        self.dims = dims
        self.root: Optional[_Node] = None
        self.size = 0

    @classmethod
    def build(cls, points) -> "KDTree":
        points = np.asarray(points, np.float64)
        tree = cls(points.shape[1])
        # median build for balance
        def rec(idxs: np.ndarray, depth: int) -> Optional[_Node]:
            if idxs.size == 0:
                return None
            axis = depth % tree.dims
            order = idxs[np.argsort(points[idxs, axis])]
            mid = order.size // 2
            node = _Node(points[order[mid]], int(order[mid]))
            node.left = rec(order[:mid], depth + 1)
            node.right = rec(order[mid + 1:], depth + 1)
            return node

        tree.root = rec(np.arange(points.shape[0]), 0)
        tree.size = points.shape[0]
        return tree

    def insert(self, point) -> None:
        point = np.asarray(point, np.float64)
        if point.shape != (self.dims,):
            raise ValueError(f"expected dim {self.dims}, got {point.shape}")
        new = _Node(point, self.size)
        self.size += 1
        if self.root is None:
            self.root = new
            return
        node, depth = self.root, 0
        while True:
            axis = depth % self.dims
            if point[axis] < node.point[axis]:
                if node.left is None:
                    node.left = new
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = new
                    return
                node = node.right
            depth += 1

    def nn(self, query) -> Tuple[float, np.ndarray]:
        """Nearest neighbor: (distance, point)."""
        res = self.knn(query, 1)
        return res[0]

    def knn(self, query, k: int) -> List[Tuple[float, np.ndarray]]:
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int, np.ndarray]] = []  # max-heap by -dist

        def rec(node: Optional[_Node], depth: int):
            if node is None:
                return
            d = float(np.linalg.norm(node.point - query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index, node.point))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index, node.point))
            axis = depth % self.dims
            diff = query[axis] - node.point[axis]
            near, far = ((node.left, node.right) if diff < 0
                         else (node.right, node.left))
            rec(near, depth + 1)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                rec(far, depth + 1)

        rec(self.root, 0)
        return sorted([(-nd, pt) for nd, _, pt in heap], key=lambda t: t[0])

    def range(self, lower, upper) -> List[np.ndarray]:
        """All points inside the axis-aligned box [lower, upper]."""
        lower = np.asarray(lower, np.float64)
        upper = np.asarray(upper, np.float64)
        out: List[np.ndarray] = []

        def rec(node: Optional[_Node], depth: int):
            if node is None:
                return
            if np.all(node.point >= lower) and np.all(node.point <= upper):
                out.append(node.point)
            axis = depth % self.dims
            if node.point[axis] >= lower[axis]:
                rec(node.left, depth + 1)
            if node.point[axis] <= upper[axis]:
                rec(node.right, depth + 1)

        rec(self.root, 0)
        return out
