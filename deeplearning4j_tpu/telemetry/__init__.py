"""Unified telemetry plane: metrics registry, step tracing, Prometheus
exposition, device gauges.

Before this package the reproduction had re-grown the reference's
observability fragmentation (SLF4J score lines + the Hazelcast
tracker's ad-hoc counters): `StepTimeListener` kept its own list,
`EngineStats` its own lock-and-dict, the guardian logged events, the
device feed counted buckets privately, and none of it shared a data
model or an export path. Now every hot path publishes into ONE
process-global `MetricsRegistry`:

- training: `dl4j_train_steps`, `dl4j_train_examples`,
  `dl4j_train_step_seconds{source=}`, `dl4j_train_loss`,
  `dl4j_train_epochs` (MultiLayerNetwork fit/fit_scan and the
  DP/ZeRO-1/TP trainers);
- guardian: `dl4j_guardian_events{kind=skip|rollback|abort|autosave|
  preempt}`;
- device feed: `dl4j_feed_batches`, `dl4j_feed_padded_examples`,
  `dl4j_feed_bucket_hits{bucket=}`, `dl4j_feed_prefetch_depth`;
- serving: `dl4j_serve_requests{engine=}`, rows/padded/errors,
  `dl4j_serve_latency_seconds`, `dl4j_serve_bucket_forwards`,
  `dl4j_batcher_*` + queue depth;
- device: `dl4j_device_memory_bytes{device=,stat=}`,
  `dl4j_jit_programs{cache=}` recompile counters;
- checkpoint: `dl4j_ckpt_saves/bytes_written/errors`,
  `dl4j_ckpt_snapshot_seconds` (step-loop stall) /
  `dl4j_ckpt_write_seconds`, in-flight + last-committed-step gauges,
  `dl4j_serve_reloads` (docs/CHECKPOINTS.md).

Export: `GET /metrics` (Prometheus text) and `GET /snapshot` (JSON) on
the serving server, the scaleout StatusServer, or a standalone
`exposition.start_metrics_server()`. Tracing: `span("train_step")`
regions with Chrome-trace export and an opt-in
`jax.profiler.TraceAnnotation` bridge (trace.py). Catalogue, scrape
quickstart and overhead envelope: docs/OBSERVABILITY.md.
"""

from deeplearning4j_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    enabled,
    get_registry,
    set_enabled,
)
from deeplearning4j_tpu.telemetry.trace import (  # noqa: F401
    SpanRecord,
    Tracer,
    active_tracer,
    chrome_trace,
    save_chrome_trace,
    span,
    start_tracing,
    stop_tracing,
    tracing,
)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "DEFAULT_BUCKETS",
    "get_registry", "set_enabled", "enabled",
    "counter", "gauge", "histogram",
    "span", "start_tracing", "stop_tracing", "tracing", "active_tracer",
    "chrome_trace", "save_chrome_trace", "Tracer", "SpanRecord",
]


def counter(name: str, help: str = ""):
    """Get-or-create a counter family on the global registry."""
    return get_registry().counter(name, help)


def gauge(name: str, help: str = ""):
    """Get-or-create a gauge family on the global registry."""
    return get_registry().gauge(name, help)


def histogram(name: str, help: str = "", **kw):
    """Get-or-create a histogram family on the global registry."""
    return get_registry().histogram(name, help, **kw)
