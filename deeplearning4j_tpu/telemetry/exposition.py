"""Prometheus text exposition + JSON snapshot of the metrics registry.

One renderer for every embedded server: the serving front end
(serving/server.py) and the scaleout StatusServer (scaleout/status.py)
both answer `GET /metrics` with `render_prometheus()` output, and
`GET /snapshot` with the JSON twin — so a Prometheus scrape config
pointed at either port sees the same catalogue
(docs/OBSERVABILITY.md). `start_metrics_server()` is the standalone
variant for processes with no HTTP surface of their own (training
entrypoints via `cli.py --metrics-port`).

Format notes (text format 0.0.4):

- counters render with the conventional `_total` suffix;
- histograms render cumulative `_bucket{le=...}` series ending in
  `le="+Inf"`, plus `_sum` and `_count`;
- label values escape backslash, double-quote and newline.
"""

from __future__ import annotations

import json
from typing import Optional

from deeplearning4j_tpu.telemetry.registry import (MetricsRegistry,
                                                   get_registry)

__all__ = [
    "CONTENT_TYPE", "render_prometheus", "snapshot", "metrics_payload",
    "handle_metrics_get", "start_metrics_server",
]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _labels_text(labels: dict, extra: Optional[tuple] = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items = items + [extra]
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:  # a NaN gauge (e.g. a diverged loss) must render, not
        return "NaN"  # 500 every scrape — the format allows literal NaN
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    i = int(f)
    return str(i) if i == f else repr(f)


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus text format 0.0.4."""
    reg = registry if registry is not None else get_registry()
    lines = []
    for fam, children in reg.collect():
        name = fam.name
        if fam.kind == "counter" and not name.endswith("_total"):
            name = name + "_total"
        if fam.help:
            lines.append(f"# HELP {name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for labels, child in children:
            if fam.kind == "histogram":
                for le, count in child.cumulative_buckets():
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_text(labels, ('le', _fmt(le)))} {count}")
                lines.append(
                    f"{name}_sum{_labels_text(labels)} {_fmt(child.sum)}")
                lines.append(
                    f"{name}_count{_labels_text(labels)} {child.count}")
            else:
                lines.append(
                    f"{name}{_labels_text(labels)} {_fmt(child.value)}")
    return "\n".join(lines) + "\n"


def snapshot(registry: Optional[MetricsRegistry] = None) -> dict:
    """JSON-safe dump of every series (the machine-readable twin of
    /metrics)."""
    reg = registry if registry is not None else get_registry()
    return reg.snapshot()


def metrics_payload(registry: Optional[MetricsRegistry] = None):
    """(body_bytes, content_type) for a /metrics response. Samples the
    device gauges (telemetry.device) so HBM pressure and recompile
    counters are one scrape away without a background sampler."""
    from deeplearning4j_tpu.telemetry import device

    device.install(registry)
    return render_prometheus(registry).encode(), CONTENT_TYPE


def handle_metrics_get(path: str,
                       registry: Optional[MetricsRegistry] = None):
    """Shared route logic for embedded servers: returns
    (code, content_type, body_bytes) for /metrics and /snapshot paths,
    or None when the path is not a telemetry route."""
    if path.startswith("/metrics"):
        body, ctype = metrics_payload(registry)
        return 200, ctype, body
    if path.startswith("/snapshot"):
        body = json.dumps(snapshot(registry)).encode()
        return 200, "application/json", body
    return None


def start_metrics_server(host: str = "127.0.0.1", port: int = 0,
                         registry: Optional[MetricsRegistry] = None):
    """Standalone /metrics + /snapshot endpoint on the shared
    utils/httpd.py lifecycle (daemon thread, port-0 auto-assign,
    graceful close). Returns the ServerHandle; the caller owns
    close()."""
    from http.server import BaseHTTPRequestHandler

    from deeplearning4j_tpu.utils.httpd import start_http_server

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def do_GET(self):
            try:
                hit = handle_metrics_get(self.path, registry)
                if hit is None:
                    code, ctype, body = 404, "text/plain", b"not found"
                else:
                    code, ctype, body = hit
            except Exception as e:  # surface, don't kill the thread
                code, ctype = 500, "text/plain"
                body = f"{type(e).__name__}: {e}".encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return start_http_server(Handler, host=host, port=port)
