"""Span-based step tracing with Chrome-trace export and an xprof bridge.

`span("train_step")` wraps a host-side region; spans nest per thread
(parent/child from a thread-local stack), clock on
`time.perf_counter_ns` (monotonic), and land in a bounded in-memory
buffer. Export is Chrome trace format (`chrome://tracing` /
Perfetto-compatible `{"traceEvents": [...]}` with "X" complete events),
so a training run's host timeline opens in the same tooling as a device
profile.

Off by default: until `start_tracing()` (or the CLI's `--trace`), a
span is a no-op context manager — a couple of attribute loads per use,
cheap enough to leave in the hot fit/serve loops permanently.

Opt-in xprof bridge: `start_tracing(jax_annotations=True)` additionally
enters `jax.profiler.TraceAnnotation(name)` for every span, so when a
`jax.profiler.trace` window is open (optimize/listeners.ProfilerListener)
the host spans line up against the device timeline in xprof — the
methodology of the array-redistribution profiling work (arXiv:2112.01075):
step phases as first-class trace data, not log lines.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List, NamedTuple, Optional

__all__ = [
    "SpanRecord", "Tracer", "span", "start_tracing", "stop_tracing",
    "tracing", "active_tracer", "chrome_trace", "save_chrome_trace",
]


class SpanRecord(NamedTuple):
    """One closed span. Times are perf_counter nanoseconds; `depth` is
    the nesting level on its thread (0 = root)."""

    name: str
    start_ns: int
    dur_ns: int
    thread_id: int
    depth: int
    args: dict


class Tracer:
    """Bounded span buffer + per-thread nesting state."""

    def __init__(self, max_spans: int = 100_000,
                 jax_annotations: bool = False):
        from collections import deque
        self.max_spans = int(max_spans)
        self.jax_annotations = bool(jax_annotations)
        self._spans = deque(maxlen=self.max_spans)
        self._local = threading.local()
        self._lock = threading.Lock()

    # ------------------------------------------------------------ record
    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def _push(self) -> int:
        d = self._depth()
        self._local.depth = d + 1
        return d

    def _pop(self) -> None:
        self._local.depth = max(0, self._depth() - 1)

    def record(self, rec: SpanRecord) -> None:
        with self._lock:
            self._spans.append(rec)

    def spans(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # ------------------------------------------------------------ export
    def chrome_trace(self) -> dict:
        """Chrome trace format dict: "X" (complete) events, microsecond
        timestamps. Nesting is reconstructed by the viewer from
        timestamp containment per tid; `depth` rides in args for
        programmatic consumers."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            args = dict(s.args)
            args["depth"] = s.depth
            events.append({
                "name": s.name,
                "ph": "X",
                "ts": s.start_ns / 1e3,
                "dur": s.dur_ns / 1e3,
                "pid": pid,
                "tid": s.thread_id,
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


_active: Optional[Tracer] = None


def start_tracing(max_spans: int = 100_000,
                  jax_annotations: bool = False) -> Tracer:
    """Install (and return) the process tracer. Idempotent-ish: a second
    call replaces the tracer (fresh buffer)."""
    global _active
    _active = Tracer(max_spans=max_spans, jax_annotations=jax_annotations)
    return _active


def stop_tracing() -> Optional[Tracer]:
    """Stop recording; returns the tracer (buffer intact) for export."""
    global _active
    t, _active = _active, None
    return t


def tracing() -> bool:
    return _active is not None


def active_tracer() -> Optional[Tracer]:
    return _active


@contextmanager
def span(name: str, **args):
    """Time a host-side region. No-op (and allocation-light) while
    tracing is off; with `jax_annotations` the region is also annotated
    onto the device timeline for xprof correlation."""
    tracer = _active
    if tracer is None:
        yield
        return
    ann = None
    if tracer.jax_annotations:
        try:
            import jax
            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
    depth = tracer._push()
    start = time.perf_counter_ns()
    try:
        yield
    finally:
        dur = time.perf_counter_ns() - start
        tracer._pop()
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        tracer.record(SpanRecord(name, start, dur,
                                 threading.get_ident(), depth, args))


def chrome_trace() -> dict:
    """Chrome trace of the active tracer ({} when tracing is off)."""
    return _active.chrome_trace() if _active else {"traceEvents": []}


def save_chrome_trace(path: str) -> Optional[str]:
    """Write the active tracer's Chrome trace; None when tracing is
    off."""
    return _active.save(path) if _active else None
