"""Process-global metrics registry: Counter / Gauge / Histogram.

The reference framework's observability stopped at SLF4J score logging
plus the Hazelcast tracker's ad-hoc counters
(BaseHazelCastStateTracker.java); this module is the single data model
every stat in the reproduction publishes into — train loops, the
guardian, the device feed, the serving engine/batcher — so one scrape
(`telemetry.exposition`) sees the whole system.

Hot-path design:

- **Counters are lock-free on the increment path**: each thread owns a
  private accumulator cell (handed out once under a lock, then cached in
  a `threading.local`), and `inc()` is a single float add on that cell —
  safe under the GIL because only the owning thread ever writes it.
  Reads (`value`, scrape) sum the cells; a scrape may lag an in-flight
  increment by one bytecode, never lose it.
- **Gauges** hold one value under a tiny lock, or a zero-arg callable
  (`set_function`) sampled at scrape time — how the device-memory and
  jit-program-cache gauges stay live without a background thread.
- **Histograms** keep fixed cumulative buckets (Prometheus semantics)
  plus a bounded reservoir for host-side percentile queries
  (`percentile(0.99)` — what EngineStats' p50/p99 read). One lock per
  observation; observations are per-request/per-step, not per-element.

A module-global kill switch (`set_enabled(False)`, or env
`DL4J_TPU_TELEMETRY=0` at import) turns every record call into an early
return — the "bare" side of `bench.py telemetry`. Instrumentation never
touches traced values either way: recording is host counters only, so
the computational path is bit-identical with telemetry on or off.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "get_registry", "set_enabled", "enabled",
    "DEFAULT_BUCKETS",
]

#: default histogram bucket bounds (seconds-flavored: 100 µs .. 10 s)
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_enabled = os.environ.get("DL4J_TPU_TELEMETRY", "1") != "0"


def set_enabled(on: bool) -> None:
    """Global record switch: False turns every inc/set/observe into an
    early return (registered series keep their last values)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter child (one labeled series)."""

    __slots__ = ("_shards", "_local", "_lock")

    def __init__(self):
        self._shards: Dict[int, list] = {}
        self._local = threading.local()
        self._lock = threading.Lock()

    def _cell(self) -> list:
        try:
            return self._local.cell
        except AttributeError:
            with self._lock:
                cell = self._shards.setdefault(
                    threading.get_ident(), [0.0])
            self._local.cell = cell
            return cell

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counters are monotonic; inc({n}) < 0")
        if not _enabled:
            return
        self._cell()[0] += n

    @property
    def value(self) -> float:
        with self._lock:
            return sum(cell[0] for cell in self._shards.values())


class Gauge:
    """Gauge child: last-set value, or a callable sampled at read."""

    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = float(v)
            self._fn = None

    def inc(self, n: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += n
            self._fn = None

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Sample `fn` at every read/scrape (live gauges: queue depth,
        device memory, jit program cache). The callable must be cheap
        and must not raise; exceptions read as the last static value."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return self._value


class Histogram:
    """Histogram child: cumulative fixed buckets + bounded percentile
    reservoir."""

    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_reservoir",
                 "_lock")

    def __init__(self, bounds: Sequence[float], window: int):
        self._bounds = tuple(bounds)
        self._counts = [0] * (len(self._bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        from collections import deque
        self._reservoir = deque(maxlen=window)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        if not _enabled:
            return
        v = float(v)
        i = 0
        for i, b in enumerate(self._bounds):
            if v <= b:
                break
        else:
            i = len(self._bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._reservoir.append(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float:
        """Percentile over the bounded reservoir (the most recent
        `window` observations); 0.0 when empty."""
        with self._lock:
            vals = sorted(self._reservoir)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
        return vals[idx]

    def cumulative_buckets(self) -> Iterable[Tuple[float, int]]:
        """[(le, cumulative_count), ..., (inf, total)] — Prometheus
        bucket semantics."""
        with self._lock:
            counts = list(self._counts)
        acc = 0
        out = []
        for bound, c in zip(self._bounds, counts):
            acc += c
            out.append((bound, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


class MetricFamily:
    """One named metric: children keyed by their label sets. Calling the
    record methods directly addresses the unlabeled child."""

    _CHILD = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, name: str, kind: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 window: int = 2048):
        self.name = name
        self.kind = kind
        self.help = help
        self._buckets = tuple(buckets)
        self._window = int(window)
        self._children: Dict[tuple, object] = {}
        self._label_names: Optional[frozenset] = None
        self._lock = threading.Lock()

    def _make_child(self):
        if self.kind == "histogram":
            return Histogram(self._buckets, self._window)
        return self._CHILD[self.kind]()

    def labels(self, **labels):
        """Get-or-create the child for this label set. Label NAMES must
        be consistent across a family (Prometheus contract); values are
        free-form and escaped at exposition."""
        names = frozenset(labels)
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self._label_names is None:
                    self._label_names = names
                elif names != self._label_names:
                    raise ValueError(
                        f"metric {self.name!r} uses label names "
                        f"{sorted(self._label_names)}, got {sorted(names)}")
                child = self._make_child()
                self._children[key] = child
            return child

    def _default(self):
        with self._lock:
            child = self._children.get(())
            if child is None:
                child = self._make_child()
                self._children[()] = child
            return child

    # unlabeled conveniences
    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default().set_function(fn)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    @property
    def value(self):
        return self._default().value

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def percentile(self, q: float) -> float:
        return self._default().percentile(q)

    def remove(self, **labels) -> None:
        """Drop one labeled series (and its history). Long-lived
        processes that churn labeled owners — serving restarts creating
        fresh engine/batcher labels — use this to cap cardinality;
        nothing calls it implicitly, because post-mortem reads of a
        closed owner's counters are part of the stats contract."""
        with self._lock:
            self._children.pop(_label_key(labels), None)

    def children(self):
        """[(labels_dict, child)] snapshot, deterministic order."""
        with self._lock:
            items = sorted(self._children.items())
        return [(dict(k), c) for k, c in items]


class MetricsRegistry:
    """Thread-safe name -> MetricFamily map with get-or-create
    semantics, so independent modules can share a family by name."""

    def __init__(self):
        self._metrics: Dict[str, MetricFamily] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, kind: str, help: str,
                       **kw) -> MetricFamily:
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                fam = MetricFamily(name, kind, help, **kw)
                self._metrics[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def counter(self, name: str, help: str = "") -> MetricFamily:
        return self._get_or_create(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> MetricFamily:
        return self._get_or_create(name, "gauge", help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  window: int = 2048) -> MetricFamily:
        return self._get_or_create(name, "histogram", help,
                                   buckets=buckets, window=window)

    def collect(self):
        """Name-sorted [(family, [(labels, child)])] snapshot."""
        with self._lock:
            fams = sorted(self._metrics.values(), key=lambda f: f.name)
        return [(fam, fam.children()) for fam in fams]

    def snapshot(self) -> dict:
        """JSON-safe dump of every series (the /snapshot API; the
        Prometheus text twin lives in telemetry.exposition)."""
        out = {}
        for fam, children in self.collect():
            series = []
            for labels, child in children:
                if fam.kind == "histogram":
                    series.append({
                        "labels": labels,
                        "count": child.count,
                        "sum": round(child.sum, 9),
                        "p50": child.percentile(0.50),
                        "p99": child.percentile(0.99),
                    })
                else:
                    series.append({"labels": labels,
                                   "value": child.value})
            out[fam.name] = {"kind": fam.kind, "help": fam.help,
                             "series": series}
        return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every built-in instrumentation point
    publishes into."""
    return _REGISTRY
