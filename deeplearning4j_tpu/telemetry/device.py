"""Per-device gauges: accelerator memory stats + recompile counters.

HBM pressure and program-cache growth are the two signals GSPMD-era
tuning decisions hang off (arXiv:2004.13336 treats per-step memory /
communication telemetry as optimization input, not log output); this
module makes both one scrape away:

- `dl4j_device_memory_bytes{device=...,stat=...}` — sampled from
  `jax.local_devices()[i].memory_stats()` at scrape time via gauge
  callables (no background thread; backends without memory stats —
  the CPU test mesh — simply render 0).
- `dl4j_jit_programs{cache=...}` — the existing
  `utils/jitcache.jit_cache_size`-backed recompile counters
  (`MultiLayerNetwork.train_step_cache_size` /
  `predict_step_cache_size`, `InferenceEngine.program_cache_size`)
  aggregated per cache label over every live owner. Owners register via
  `watch_jit_cache`; bound-method probes are held through weakrefs so
  watching never extends a network's or engine's lifetime. A probe
  returning -1 (jax private API drift) makes the whole label read -1 —
  "counter unavailable", never a fake 0.

`install()` is idempotent and cheap; `exposition.metrics_payload` calls
it so any /metrics mount gets device series without extra wiring.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.telemetry.registry import (MetricsRegistry,
                                                   get_registry)

__all__ = ["install", "watch_jit_cache", "jit_cache_total"]

_MEM_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

_lock = threading.Lock()
_watches: Dict[str, List] = {}
_installed_on: "weakref.WeakSet" = weakref.WeakSet()


def _probe_ref(probe: Callable[[], int]):
    """Weakly reference a bound-method probe (the common case: a
    network's / engine's cache-size method); plain callables are held
    strongly — callers own their lifetime."""
    if hasattr(probe, "__self__"):
        return weakref.WeakMethod(probe)
    return lambda: probe


def watch_jit_cache(label: str, probe: Callable[[], int],
                    registry: Optional[MetricsRegistry] = None) -> None:
    """Aggregate `probe()` (a jit_cache_size-style compiled-program
    counter) into the `dl4j_jit_programs{cache=label}` gauge. Many
    owners may share one label (every MultiLayerNetwork watches
    "train_step"); dead owners fall out via their weakrefs."""
    reg = registry if registry is not None else get_registry()
    with _lock:
        refs = _watches.setdefault(label, [])
        refs.append(_probe_ref(probe))
        if len(refs) > 64:  # prune dead owners opportunistically
            refs[:] = [r for r in refs if r() is not None]
    reg.gauge(
        "dl4j_jit_programs",
        "compiled XLA programs per jitted-function cache (-1: counter "
        "unavailable)",
    ).labels(cache=label).set_function(lambda: jit_cache_total(label))


def jit_cache_total(label: str) -> int:
    """Sum of live probes under `label`; -1 if any live probe reports
    the private jax counter API drifted."""
    with _lock:
        refs = list(_watches.get(label, ()))
    total = 0
    for ref in refs:
        probe = ref()
        if probe is None:
            continue
        try:
            size = int(probe())
        except Exception:
            continue
        if size < 0:
            return -1
        total += size
    return total


def install(registry: Optional[MetricsRegistry] = None) -> None:
    """Register the device gauges on `registry` (default: the global).
    Idempotent per registry; gauge callables sample live at scrape."""
    reg = registry if registry is not None else get_registry()
    if reg in _installed_on:
        return
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return  # no backend yet: try again at the next scrape
    _installed_on.add(reg)

    reg.gauge("dl4j_device_count",
              "local accelerator devices").set(len(devices))
    mem = reg.gauge(
        "dl4j_device_memory_bytes",
        "per-device memory stats sampled from jax memory_stats()")
    for d in devices:
        for stat in _MEM_STATS:
            def sample(_d=d, _s=stat) -> float:
                try:
                    stats = _d.memory_stats()
                except Exception:
                    stats = None
                return float((stats or {}).get(_s, 0))

            mem.labels(device=str(d), stat=stat).set_function(sample)
