"""Self-attention layer for the layer registry.

Beyond-reference capability (the reference predates attention): a
single-head self-attention block usable in a MultiLayerNetwork stack on
(batch, T, d) inputs. The forward computes through `flash_attention` —
the Pallas kernel on TPU for tile-aligned sequences, transparently the
blockwise form elsewhere (same O(T) memory either way; the custom VJP
recomputes through blockwise). With a mesh configured, callers can swap
the inner call for `ring_attention` (sequence parallelism).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.flash_pallas import flash_attention
from deeplearning4j_tpu.nn.layers import (BaseLayer, apply_dropout,
                                          register_layer)


@register_layer("self_attention")
class SelfAttentionLayer(BaseLayer):
    """Wq/Wk/Wv projections + flash-style attention + Wo output proj.
    Config: n_in = model dim, n_out = head dim (defaults to n_in),
    `causal` = causal masking. Params init through BaseLayer.init_params
    (none are bias-named, so all four get the weight-init scheme)."""

    def _dims(self):
        d_model = self.conf.n_in
        d_head = self.conf.n_out or d_model
        return d_model, d_head

    def is_causal(self) -> bool:
        return bool(self.conf.causal)

    def param_shapes(self) -> Dict[str, tuple]:
        d_model, d_head = self._dims()
        return {"Wq": (d_model, d_head), "Wk": (d_model, d_head),
                "Wv": (d_model, d_head), "Wo": (d_head, d_model)}

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        """x: (B, T, d_model) -> (B, T, d_model)."""
        if x.ndim != 3:
            raise ValueError(
                f"self_attention expects (batch, time, dim), got {x.shape}")
        cd = jnp.dtype(self.conf.compute_dtype)
        q = (x.astype(cd) @ params["Wq"].astype(cd))
        k = (x.astype(cd) @ params["Wk"].astype(cd))
        v = (x.astype(cd) @ params["Wv"].astype(cd))
        # interpret mode off-TPU: the kernel path still runs (slowly) under
        # the Pallas interpreter so tests exercise the same code path
        on_tpu = jax.devices()[0].platform == "tpu"
        out = flash_attention(q, k, v, causal=self.is_causal(),
                              interpret=not on_tpu)
        out = out.astype(jnp.dtype(self.conf.dtype)) @ params["Wo"]
        return apply_dropout(rng, out, self.conf.dropout, training)
