"""Self-attention layer for the layer registry.

Beyond-reference capability (the reference predates attention): a
single-head self-attention block usable in a MultiLayerNetwork stack on
(batch, T, d) inputs. The forward computes through `flash_attention` —
the Pallas kernel on TPU for tile-aligned sequences, transparently the
blockwise form elsewhere (same O(T) memory either way; the custom VJP
recomputes through blockwise). With a mesh configured, callers can swap
the inner call for `ring_attention` (sequence parallelism).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.flash_pallas import flash_attention
from deeplearning4j_tpu.nn.layers import (BaseLayer, apply_dropout,
                                          register_layer)


@register_layer("self_attention")
class SelfAttentionLayer(BaseLayer):
    """Wq/Wk/Wv projections + flash attention + Wo output proj.
    Config: n_in = model dim, n_out = total attention dim (defaults to
    n_in), n_heads = attention heads (n_out divisible by it), `causal` =
    causal masking. Params init through BaseLayer.init_params (none are
    bias-named, so all four get the weight-init scheme)."""

    def _dims(self):
        d_model = self.conf.n_in
        d_attn = self.conf.n_out or d_model
        n_heads = max(1, int(getattr(self.conf, "n_heads", 1)))
        if d_attn % n_heads:
            raise ValueError(
                f"attention dim {d_attn} not divisible by "
                f"n_heads {n_heads}")
        return d_model, d_attn, n_heads

    def is_causal(self) -> bool:
        return bool(self.conf.causal)

    def param_shapes(self) -> Dict[str, tuple]:
        d_model, d_attn, _ = self._dims()
        return {"Wq": (d_model, d_attn), "Wk": (d_model, d_attn),
                "Wv": (d_model, d_attn), "Wo": (d_attn, d_model)}

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        """x: (B, T, d_model) -> (B, T, d_model)."""
        if x.ndim != 3:
            raise ValueError(
                f"self_attention expects (batch, time, dim), got {x.shape}")
        _, d_attn, n_heads = self._dims()
        d_head = d_attn // n_heads
        B, T, _ = x.shape
        cd = jnp.dtype(self.conf.compute_dtype)

        def heads(w):
            # (B, T, d_attn) -> (B, H, T, d_head)
            proj = x.astype(cd) @ w.astype(cd)
            return proj.reshape(B, T, n_heads, d_head).transpose(0, 2, 1, 3)

        q, k, v = heads(params["Wq"]), heads(params["Wk"]), heads(params["Wv"])
        # interpret mode off-TPU: the kernel path still runs (slowly) under
        # the Pallas interpreter so tests exercise the same code path
        on_tpu = jax.devices()[0].platform == "tpu"
        out = flash_attention(q, k, v, causal=self.is_causal(),
                              interpret=not on_tpu)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, d_attn)
        out = out.astype(jnp.dtype(self.conf.dtype)) @ params["Wo"]
        return apply_dropout(rng, out, self.conf.dropout, training)
