"""Long-context attention: blockwise (flash-style), Pallas kernel, and
ring attention over a device mesh.

The reference predates attention entirely (SURVEY §5: its long-sequence
story is an unrolled LSTM + moving windows), so this package is the
TPU-first capability the survey's charter adds: sequence/context
parallelism that scales past one chip's HBM.

Design:
- `blockwise_attention` — online-softmax attention scanned over KV blocks
  (the FlashAttention recurrence) in pure JAX; O(T) memory in sequence
  length, differentiable, fuses under jit.
- `flash_attention` — the same recurrence as a hand-tiled Pallas TPU
  kernel (MXU-shaped 128-lane tiles, VMEM accumulators), with a
  custom-VJP backward that recomputes via the blockwise form.
- `ring_attention` — sequence-parallel attention inside shard_map: each
  device holds a sequence shard of Q/K/V and K/V blocks rotate around the
  mesh axis via `lax.ppermute` (ICI neighbor exchange) while every device
  accumulates its queries' online softmax. Full attention over sequences
  n_devices times longer than one chip could hold.
"""

from deeplearning4j_tpu.attention.blockwise import (  # noqa: F401
    blockwise_attention,
    naive_attention,
)
from deeplearning4j_tpu.attention.flash_pallas import flash_attention  # noqa: F401
from deeplearning4j_tpu.attention.ring import ring_attention  # noqa: F401
from deeplearning4j_tpu.attention.layer import SelfAttentionLayer  # noqa: F401
