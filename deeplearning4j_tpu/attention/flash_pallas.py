"""Flash attention as a Pallas TPU kernel.

Forward: grid (batch*heads, Q tiles, KV blocks) — the TPU grid is
sequential over the last dimension, so the kernel streams (block_k, d)
K/V tiles through VMEM while float32 scratch accumulators carry the
online-softmax state (acc, m, s) across KV steps for the current Q tile;
the output tile is finalized on the last KV step. Causal tiles entirely
above the diagonal are skipped (no MXU work). Backward: custom VJP that
recomputes through the pure-JAX blockwise form (FlashAttention's standard
recompute strategy — residuals are just q, k, v).

Falls back to `blockwise_attention` for tile-indivisible shapes
(interpret mode covers CPU tests).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.blockwise import blockwise_attention

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, s_ref, *,
            causal: bool, q_tile: int, block_k: int, causal_offset: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        s_ref[...] = jnp.zeros_like(s_ref)

    # causal skip: this KV block starts after the last key visible to the
    # tile's last query (bottom-right alignment: query i sees keys up to
    # i + causal_offset, causal_offset = Tk - Tq — matches blockwise;
    # fully-masked rows output 0 like blockwise, unlike naive's mean-of-V).
    if causal:
        skip = ki * block_k > (qi + 1) * q_tile - 1 + causal_offset
    else:
        skip = jnp.asarray(False)

    @pl.when(jnp.logical_not(skip))
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (q_tile, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        d = q.shape[-1]
        scale = 1.0 / jnp.float32(d) ** 0.5
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * q_tile + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile, block_k), 1)
            mask = k_pos <= q_pos + causal_offset
            scores = jnp.where(mask, scores, NEG_INF)
        m_prev, s_prev = m_ref[...], s_ref[...]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        m_ref[...] = m_new
        s_ref[...] = s_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(s_ref[...], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, q_tile: int, block_k: int,
                   interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t_q, d = q.shape
    t_k = k.shape[1]
    grid = (b, t_q // q_tile, t_k // block_k)
    return pl.pallas_call(
        partial(_kernel, causal=causal, q_tile=q_tile, block_k=block_k,
                causal_offset=t_k - t_q),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, q_tile, d), lambda bi, qi, ki: (bi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bi, qi, ki: (bi, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bi, qi, ki: (bi, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, q_tile, d),
                               lambda bi, qi, ki: (bi, qi, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((q_tile, d), jnp.float32),   # acc
            pltpu.VMEM((q_tile, 1), jnp.float32),   # running max
            pltpu.VMEM((q_tile, 1), jnp.float32),   # running sum
        ],
        interpret=interpret,
    )(q, k, v)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, q_tile: int = 256,
                    block_k: int = 512, interpret: bool = False):
    """Pallas flash attention. q/k/v: (batch[*heads], T, d). Tile sizes
    clamp to T, so short sequences stay on the kernel; T not divisible
    by the (clamped) tiles falls back to blockwise. Set interpret=True
    off-TPU.

    Defaults tuned on v5e at (4x8)x2048x64 bf16 causal: 256/512 measured
    ~1.4x faster than 128/128 (11.3 vs 16.0 ms with hard D2H sync).

    NOTE: sequence length is axis -2 (NOT axis 1 — a 4-D (B, H, T, d)
    input's axis 1 is heads; reading it as T silently routed every 4-D
    call to the blockwise fallback)."""
    t_q, t_k = q.shape[-2], k.shape[-2]
    # clamp tiles to shorter sequences, but only lane-aligned ones —
    # ragged lengths go to the blockwise fallback
    if t_q < q_tile and t_q % 128 == 0:
        q_tile = t_q
    if t_k < block_k and t_k % 128 == 0:
        block_k = t_k
    if t_q % q_tile or t_k % block_k:
        return blockwise_attention(q, k, v, causal=causal)
    out = _flash_forward(q.reshape(-1, t_q, q.shape[-1]),
                         k.reshape(-1, t_k, k.shape[-1]),
                         v.reshape(-1, t_k, v.shape[-1]),
                         causal, q_tile, block_k, interpret)
    return out.reshape(q.shape)


def _fwd(q, k, v, causal, q_tile, block_k, interpret):
    return (flash_attention(q, k, v, causal, q_tile, block_k, interpret),
            (q, k, v))


def _bwd(causal, q_tile, block_k, interpret, res, g):
    q, k, v = res
    # FlashAttention recompute strategy: differentiate the blockwise form
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
