"""Flash attention as Pallas TPU kernels, forward and backward.

Forward: grid (batch*heads, Q tiles, KV blocks) — the TPU grid is
sequential over the last dimension, so the kernel streams (block_k, d)
K/V tiles through VMEM while float32 scratch accumulators carry the
online-softmax state (acc, m, s) across KV steps for the current Q tile;
the output tile (and the per-row log-sum-exp, saved for backward) is
finalized on the last KV step. Causal tiles entirely above the diagonal
are skipped (no MXU work).

Backward: the FlashAttention recompute strategy with the saved LSE —
P = exp(S − lse) is rebuilt tile-by-tile (never materializing the full
score matrix), D = rowsum(dO ∘ O) precomputed outside. Two kernels:
dQ iterates KV blocks per Q tile; dK/dV iterates Q tiles per KV block
(each with the matching causal skip).

All dots run with bf16 operands (f32 accumulation via
preferred_element_type) — the v5e MXU's native mode; softmax state is
f32 in base-2 (exp2). Causal masking only runs on diagonal-crossing
blocks; fully-visible blocks take a mask-free branch. Measured numbers
and the amortized chained-scan timing protocol: BASELINE.md.

Falls back to `blockwise_attention` (forward AND backward) for
tile-indivisible shapes; interpret mode covers CPU tests on the same
kernel code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.blockwise import blockwise_attention

NEG_INF = -1e30
LANES = 128  # Mosaic-aligned trailing dim for row vectors (lse, D)


def _tpu_compiler_params(pltpu, **kw):
    """pltpu.CompilerParams across the rename (TPUCompilerParams on
    older jax releases)."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)
LOG2E = 1.4426950408889634   # softmax state is kept in base-2 (exp2)
LN2 = 0.6931471805599453     # converts base-2 LSE back to natural log


def _fit_tile(t: int, tile: int):
    """Largest 128-aligned divisor of t that is <= tile.

    Returns None when no such divisor exists (ragged t — caller falls
    back to blockwise). This keeps lengths like 768 or 1536 on the
    kernel with a smaller tile instead of silently demoting them to the
    fallback when they don't divide the default tile.

    Degenerate t == 1 (a decode-shaped single-row query) returns 1: the
    tile dim is a Mosaic SUBLANE dim, which pads 1 -> 8 internally, so
    a one-row tile is legal and costs one row of padding — not a full
    q_tile of it, and not a demotion to the dense fallback. Other
    sub-128 lengths still fall back (their padding story is unmeasured
    and the prefill buckets never produce them on the kernel path)."""
    for c in range(tile - tile % 128, 0, -128):
        if c <= t and t % c == 0:
            return c
    if t == 1:
        return 1
    return None


def _causal_branches(causal: bool, qi, ki, q_tile: int, block_k: int,
                     causal_offset: int):
    """(visible, diagonal) predicates for one grid step: `visible` =
    every element of this KV block is on or below the diagonal for every
    query of the tile (mask-free branch); `diagonal` = the block crosses
    the diagonal (iota/compare/where masking required). Blocks entirely
    above the diagonal fire neither branch — the causal skip."""
    if not causal:
        return jnp.asarray(True), jnp.asarray(False)
    skip = ki * block_k > (qi + 1) * q_tile - 1 + causal_offset
    diagonal = jnp.logical_and(
        jnp.logical_not(skip),
        ki * block_k + block_k - 1 > qi * q_tile + causal_offset)
    visible = jnp.logical_and(jnp.logical_not(skip),
                              jnp.logical_not(diagonal))
    return visible, diagonal


def _kernel(q_ref, k_ref, v_ref, o_ref, *rest, causal: bool, q_tile: int,
            block_k: int, causal_offset: int, group: int, want_lse: bool):
    from jax.experimental import pallas as pl

    if want_lse:
        lse_ref, acc_ref, m_ref, s_ref = rest
    else:
        lse_ref = None
        acc_ref, m_ref, s_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        s_ref[...] = jnp.zeros_like(s_ref)

    # causal semantics: bottom-right alignment — query i sees keys up to
    # i + causal_offset, causal_offset = Tk - Tq (matches blockwise;
    # fully-masked rows output 0 like blockwise, unlike naive's
    # mean-of-V). Blocks entirely BELOW the diagonal take the mask-free
    # branch: the per-block iota/compare/where VPU work only runs on
    # diagonal-crossing blocks.
    visible, diagonal = _causal_branches(
        causal, qi, ki, q_tile, block_k, causal_offset)

    def _tile_update(masked: bool):
        # operands stay in their storage dtype (bf16): the v5e MXU runs
        # bf16 matmuls at full rate with f32 accumulation
        # (preferred_element_type) — casting to f32 first quarters MXU
        # throughput. Softmax state is f32 throughout, kept in base-2
        # (scores pre-scaled by log2(e)/sqrt(d), exp2 instead of exp) so
        # the transcendental is a bare exp2 with no hidden multiply.
        # `group` batch rows (heads) are processed per grid step as a
        # batched dot: the round-5 ablation measured the kernel
        # MXU-dot + per-step-overhead bound (NOT VPU-softmax bound as
        # round 4's broken-protocol ablation claimed), and halving the
        # grid-step count amortizes that overhead (0.547 -> 0.462 ms at
        # 4x8x2048x64 with group=2, q_tile=1024).
        q = q_ref[...]  # (group, q_tile, d)
        k = k_ref[...]  # (group, block_k, d)
        v = v_ref[...]
        d = q.shape[-1]
        scale2 = jnp.float32(LOG2E) / jnp.float32(d) ** 0.5
        scores = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale2
        if masked:
            q_pos = qi * q_tile + jax.lax.broadcasted_iota(
                jnp.int32, (group, q_tile, block_k), 1)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (group, q_tile, block_k), 2)
            mask = k_pos <= q_pos + causal_offset
            scores = jnp.where(mask, scores, NEG_INF)
        m_prev, s_prev = m_ref[...], s_ref[...]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(scores - m_new)
        if masked:
            p = jnp.where(mask, p, 0.0)  # fully-masked rows: m_new=NEG_INF
        m_ref[...] = m_new
        s_ref[...] = s_prev * alpha + p.sum(axis=-1, keepdims=True)
        # P is cast to V's storage dtype for the second MXU dot (standard
        # flash formulation; accumulation stays f32 so the bf16 rounding
        # of P costs ~2^-8 relative — inside bf16 output tolerance)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(visible)
    def _compute_unmasked():
        _tile_update(masked=False)

    if causal:
        @pl.when(diagonal)
        def _compute_masked():
            _tile_update(masked=True)

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] /
                      jnp.maximum(s_ref[...], 1e-30)).astype(o_ref.dtype)
        if want_lse:
            # log-sum-exp per row, saved for the backward kernels
            # (FlashAttention's L = m + log s). Fully-masked rows (s == 0)
            # get a large sentinel so exp(S - lse) underflows to exactly
            # 0. Stored lane-broadcast (group, q_tile, LANES) — Mosaic
            # block shapes need a 128-divisible trailing dim.
            s = s_ref[...]
            # m is tracked in base-2 (see _tile_update); convert to the
            # natural-log LSE the backward kernels expect: ln2·m + ln(s)
            lse = jnp.where(s > 0.0,
                            jnp.float32(LN2) * m_ref[...]
                            + jnp.log(jnp.maximum(s, 1e-30)),
                            jnp.float32(-NEG_INF))  # (group, q_tile, 1)
            lse_ref[...] = jnp.broadcast_to(lse, (*lse.shape[:-1], LANES))


def _flash_forward(q, k, v, causal: bool, q_tile: int, block_k: int,
                   interpret: bool, want_lse: bool = True):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t_q, d = q.shape
    t_k = k.shape[1]
    # Pair up batch rows (heads) when the batch divides and VMEM allows:
    # a (2, tile, d) batched dot halves the grid-step count, amortizing
    # the per-step overhead the round-5 ablation measured (0.547 ->
    # 0.462 ms at 4x8x2048x64). VMEM estimate per grid step at group g:
    # f32 scores (g*qt*bk*4) + double-buffered bf16 q/k/v/o blocks
    # (d-scaled) + f32 acc scratch + the lse output block on the vjp
    # path. The estimate undercounts Mosaic's internal buffers, so the
    # threshold is CALIBRATED on d=64 1024x1024 measurements: the
    # no-lse group=2 config (estimate 10.6M) compiles and runs; the lse
    # group=2 config (estimate 12.7M) OOMs at 17.71M actual against the
    # 16M scoped limit. 11.5M sits between them, erring conservative
    # (larger d falls back to the always-safe group=1).
    def vmem_est(g):
        itemsize = q.dtype.itemsize  # kernel blocks stay in input dtype
        scores = g * q_tile * block_k * 4
        io = 2 * g * (q_tile + 2 * block_k + q_tile) * d * itemsize
        acc = g * q_tile * d * 4
        lse = 2 * g * q_tile * LANES * 4 if want_lse else 0
        return scores + io + acc + lse

    # group=2 only inside the envelope the 11.5M threshold was actually
    # calibrated on (d <= 64, <= 2-byte operands): outside it the
    # estimate's undercount of Mosaic's internal buffers is unvalidated,
    # and a miss is a runtime Mosaic VMEM OOM rather than a graceful
    # fallback — degrade to the always-safe group=1 instead
    group = 2 if (b % 2 == 0 and d <= 64 and q.dtype.itemsize <= 2
                  and vmem_est(2) <= 11.5 * 1024 * 1024) else 1
    grid = (b // group, t_q // q_tile, t_k // block_k)
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [pl.BlockSpec((group, q_tile, d),
                              lambda bi, qi, ki: (bi, qi, 0),
                              memory_space=pltpu.VMEM)]
    if want_lse:
        out_shape.append(
            jax.ShapeDtypeStruct((b, t_q, LANES), jnp.float32))
        out_specs.append(pl.BlockSpec((group, q_tile, LANES),
                                      lambda bi, qi, ki: (bi, qi, 0),
                                      memory_space=pltpu.VMEM))
    res = pl.pallas_call(
        partial(_kernel, causal=causal, q_tile=q_tile, block_k=block_k,
                causal_offset=t_k - t_q, group=group, want_lse=want_lse),
        out_shape=tuple(out_shape) if want_lse else out_shape[0],
        grid=grid,
        in_specs=[
            pl.BlockSpec((group, q_tile, d),
                         lambda bi, qi, ki: (bi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((group, block_k, d),
                         lambda bi, qi, ki: (bi, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((group, block_k, d),
                         lambda bi, qi, ki: (bi, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(out_specs) if want_lse else out_specs[0],
        scratch_shapes=[
            pltpu.VMEM((group, q_tile, d), jnp.float32),   # acc
            pltpu.VMEM((group, q_tile, 1), jnp.float32),   # running max
            pltpu.VMEM((group, q_tile, 1), jnp.float32),   # running sum
        ],
        # batch and Q-tile grid dims carry no cross-step state — letting
        # Mosaic treat them as parallel measured ~1.4x on v5e; only the
        # KV accumulation dim is sequential
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return res if want_lse else (res, None)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, q_tile: int = 1024,
                    block_k: int = 1024, interpret: bool = False):
    """Pallas flash attention. q/k/v: (batch[*heads], T, d). Tile sizes
    fit to T (largest 128-aligned divisor <= the requested tile), so
    short or oddly-sized-but-aligned sequences stay on the kernel; T
    with no 128-aligned divisor falls back to blockwise. Set
    interpret=True off-TPU.

    Defaults tuned on v5e at (4x8)x2048x64 bf16 causal under the
    amortized chained-scan protocol (see BASELINE.md). Round-5 ablation:
    the kernel is MXU-dot + per-grid-step-overhead bound (dots-only on
    the same grid: 0.50 ms of the 0.63 ms non-causal total; an empty
    kernel body is 0.11 ms), so fewer/larger steps win: q_tile 1024 +
    batch-pair grouping (see _flash_forward) moved 0.547 -> 0.462
    ms/step causal. bf16 softmax, score prescaling, and a
    double-buffered lookahead pipeline were all measured no-better
    (scratch/flash_ablate3.py).

    NOTE: sequence length is axis -2 (NOT axis 1 — a 4-D (B, H, T, d)
    input's axis 1 is heads; reading it as T silently routed every 4-D
    call to the blockwise fallback)."""
    t_q, t_k = q.shape[-2], k.shape[-2]
    # fit tiles: largest 128-aligned divisor <= the requested tile, so
    # e.g. T=768 runs the kernel at tile 384 instead of falling back;
    # truly ragged lengths go to the blockwise fallback
    q_tile = _fit_tile(t_q, q_tile)
    block_k = _fit_tile(t_k, block_k)
    if q_tile is None or block_k is None:
        return blockwise_attention(q, k, v, causal=causal)
    # primal/inference path: no lse output — skips the extra output
    # block + finalize log, which is what lets batch-pair grouping fit
    # VMEM at the 1024x1024 tiles (the vjp fwd below pays for the lse)
    out, _ = _flash_forward(q.reshape(-1, t_q, q.shape[-1]),
                            k.reshape(-1, t_k, k.shape[-1]),
                            v.reshape(-1, t_k, v.shape[-1]),
                            causal, q_tile, block_k, interpret,
                            want_lse=False)
    return out.reshape(q.shape)


# --------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref,
                   dq_acc, *, causal: bool, q_tile: int, block_k: int,
                   causal_offset: int):
    """dQ: grid (b, Tq/q_tile, Tk/block_k); accumulate over KV blocks.
    dS = P ∘ (dP − D); dQ = dS @ K · scale  (FlashAttention bwd, with
    P recomputed from the saved row log-sum-exp)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    visible, diagonal = _causal_branches(
        causal, qi, ki, q_tile, block_k, causal_offset)

    def _tile_update(masked: bool):
        # bf16 MXU operands with f32 accumulation, like the forward;
        # P recomputed in base-2 from the saved natural-log LSE. As in
        # the forward, the iota/compare/where masking only runs on
        # diagonal-crossing blocks.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]        # (q_tile,) lane-broadcast store
        dd = dd_ref[0][:, 0]          # (q_tile,) rowsum(dO ∘ O)
        d = q.shape[-1]
        scale = 1.0 / jnp.float32(d) ** 0.5
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * jnp.float32(LOG2E))
        if masked:
            q_pos = qi * q_tile + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile, block_k), 1)
            s = jnp.where(k_pos <= q_pos + causal_offset, s, NEG_INF)
        p = jnp.exp2(s - (lse * jnp.float32(LOG2E))[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dd[:, None])
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(visible)
    def _compute_unmasked():
        _tile_update(masked=False)

    if causal:
        @pl.when(diagonal)
        def _compute_masked():
            _tile_update(masked=True)

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                    q_tile: int, block_k: int, causal_offset: int):
    """dK/dV: grid (b, Tk/block_k, Tq/q_tile); accumulate over Q tiles.
    dV = Pᵀ @ dO; dK = dSᵀ @ Q · scale."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    visible, diagonal = _causal_branches(
        causal, qi, ki, q_tile, block_k, causal_offset)

    def _tile_update(masked: bool):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        dd = dd_ref[0][:, 0]
        d = q.shape[-1]
        scale = 1.0 / jnp.float32(d) ** 0.5
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * jnp.float32(LOG2E))
        if masked:
            q_pos = qi * q_tile + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile, block_k), 1)
            s = jnp.where(k_pos <= q_pos + causal_offset, s, NEG_INF)
        p = jnp.exp2(s - (lse * jnp.float32(LOG2E))[:, None])
        pb = p.astype(do.dtype)                      # (q_tile, block_k)
        dv_acc[...] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dd[:, None])
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(visible)
    def _compute_unmasked():
        _tile_update(masked=False)

    if causal:
        @pl.when(diagonal)
        def _compute_masked():
            _tile_update(masked=True)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, q_tile: int,
                    block_k: int, interpret: bool, lse_grad=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t_q, d = q.shape
    t_k = k.shape[1]
    dd = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                 axis=-1)  # (b, t_q): rowsum(dO ∘ O)
    if lse_grad is not None:
        # joint (out, lse) cotangent: d lse/d s_j = p_j, so the lse
        # term enters ds = p*(dp - dd) as a -g_lse shift of dd
        dd = dd - lse_grad.astype(jnp.float32)
    dd = jnp.broadcast_to(dd[..., None], (*dd.shape, LANES))

    q_spec = pl.BlockSpec((1, q_tile, d), memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, d), memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, q_tile, LANES), memory_space=pltpu.VMEM)

    def at(index_map, spec):
        return pl.BlockSpec(spec.block_shape, index_map,
                            memory_space=pltpu.VMEM)

    common = dict(causal=causal, q_tile=q_tile, block_k=block_k,
                  causal_offset=t_k - t_q)

    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(b, t_q // q_tile, t_k // block_k),
        in_specs=[
            at(lambda bi, qi, ki: (bi, qi, 0), q_spec),    # q
            at(lambda bi, qi, ki: (bi, ki, 0), k_spec),    # k
            at(lambda bi, qi, ki: (bi, ki, 0), k_spec),    # v
            at(lambda bi, qi, ki: (bi, qi, 0), q_spec),    # dO
            at(lambda bi, qi, ki: (bi, qi, 0), row_spec),  # lse
            at(lambda bi, qi, ki: (bi, qi, 0), row_spec),  # D
        ],
        out_specs=at(lambda bi, qi, ki: (bi, qi, 0), q_spec),
        scratch_shapes=[pltpu.VMEM((q_tile, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, dd)

    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        grid=(b, t_k // block_k, t_q // q_tile),
        in_specs=[
            at(lambda bi, ki, qi: (bi, qi, 0), q_spec),    # q
            at(lambda bi, ki, qi: (bi, ki, 0), k_spec),    # k
            at(lambda bi, ki, qi: (bi, ki, 0), k_spec),    # v
            at(lambda bi, ki, qi: (bi, qi, 0), q_spec),    # dO
            at(lambda bi, ki, qi: (bi, qi, 0), row_spec),  # lse
            at(lambda bi, ki, qi: (bi, qi, 0), row_spec),  # D
        ],
        out_specs=(at(lambda bi, ki, qi: (bi, ki, 0), k_spec),
                   at(lambda bi, ki, qi: (bi, ki, 0), k_spec)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, dd)
    return dq, dk, dv


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             q_tile: int = 1024, block_k: int = 1024,
                             interpret: bool = False):
    """Flash attention returning (out, lse) — lse[i] = log sum_j
    exp(s_ij) per query row (natural log, scaled scores). The building
    block for cross-shard softmax combines (ring attention's per-step
    merge, flash-decoding style splits): partial results from disjoint
    KV shards merge exactly via
    m = max(lse_a, lse_b); out = (exp(lse_a-m) out_a + exp(lse_b-m)
    out_b) / (exp(lse_a-m) + exp(lse_b-m)).

    CAVEAT: a query row that sees NO keys in its shard (causal split
    where the whole shard is in the row's future) gets out = 0 and
    lse = +1e30 — a sentinel, NOT the -inf merge identity. Substitute
    lse = -inf (and out = 0) for such shards before merging, as
    attention/ring.py's `future` branch does.

    Differentiable jointly in (out, lse): d lse / d s_j = p_j, so the
    lse cotangent folds into the existing backward as
    ds = p * (dp - (rowsum(dO*O) - g_lse)) — i.e. the dd term passed to
    the dQ/dKV kernels is shifted by -g_lse and nothing else changes.
    """
    t_q, t_k = q.shape[-2], k.shape[-2]
    qt = _fit_tile(t_q, q_tile)
    bk = _fit_tile(t_k, block_k)
    if qt is None or bk is None:
        return _blockwise_with_lse(q, k, v, causal)
    out3, lse3 = _flash_forward(q.reshape(-1, t_q, q.shape[-1]),
                                k.reshape(-1, t_k, k.shape[-1]),
                                v.reshape(-1, t_k, v.shape[-1]),
                                causal, qt, bk, interpret, want_lse=True)
    return (out3.reshape(q.shape),
            lse3[..., 0].reshape(*q.shape[:-1]))


def _blockwise_with_lse(q, k, v, causal):
    """Fallback (out, lse) for kernel-ineligible shapes: the online
    blockwise scan with its carry's lse read off — O(block) working
    set, same +1e30 sentinel for fully-masked rows as the kernel."""
    return blockwise_attention(q, k, v, causal=causal, return_lse=True)


def _fwd_with_lse(q, k, v, causal, q_tile, block_k, interpret):
    out, lse = flash_attention_with_lse(q, k, v, causal, q_tile,
                                        block_k, interpret)
    if (_fit_tile(q.shape[-2], q_tile) is None
            or _fit_tile(k.shape[-2], block_k) is None):
        # blockwise-fallback shapes: the backward re-derives everything
        # via jax.vjp — don't hold the (out, lse) activations alive
        return (out, lse), (q, k, v, None, None)
    return (out, lse), (q, k, v, out, lse)


def _bwd_with_lse(causal, q_tile, block_k, interpret, res, g):
    g_out, g_lse = g
    q, k, v, out, lse = res
    t_q, t_k = q.shape[-2], k.shape[-2]
    qt = _fit_tile(t_q, min(q_tile, 512))
    bk = _fit_tile(t_k, block_k)
    if out is None or qt is None or bk is None:
        # shapes that fell back in the forward differentiate the
        # blockwise form (including the lse output)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _blockwise_with_lse(q_, k_, v_, causal),
            q, k, v)
        return vjp((g_out, g_lse))
    out3 = out.reshape(-1, t_q, q.shape[-1])
    lse3 = jnp.broadcast_to(
        lse.reshape(-1, t_q)[..., None], (*lse.reshape(-1, t_q).shape,
                                          LANES))
    dq, dk, dv = _flash_backward(
        q.reshape(-1, t_q, q.shape[-1]), k.reshape(-1, t_k, k.shape[-1]),
        v.reshape(-1, t_k, v.shape[-1]), out3, lse3,
        g_out.reshape(-1, t_q, q.shape[-1]), causal, qt, bk, interpret,
        lse_grad=g_lse.reshape(-1, t_q))
    return dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape)


flash_attention_with_lse.defvjp(_fwd_with_lse, _bwd_with_lse)


def _fwd(q, k, v, causal, q_tile, block_k, interpret):
    t_q, t_k = q.shape[-2], k.shape[-2]
    qt = _fit_tile(t_q, q_tile)
    bk = _fit_tile(t_k, block_k)
    if qt is None or bk is None:
        # ragged: forward used the blockwise fallback — backward must too
        out = blockwise_attention(q, k, v, causal=causal)
        return out, (q, k, v, None, None)
    out3, lse = _flash_forward(q.reshape(-1, t_q, q.shape[-1]),
                               k.reshape(-1, t_k, k.shape[-1]),
                               v.reshape(-1, t_k, v.shape[-1]),
                               causal, qt, bk, interpret)
    return out3.reshape(q.shape), (q, k, v, out3, lse)


def _bwd(causal, q_tile, block_k, interpret, res, g):
    q, k, v, out3, lse = res
    if out3 is None:
        # blockwise-fallback forward: differentiate the blockwise form
        _, vjp = jax.vjp(
            lambda q_, k_, v_: blockwise_attention(q_, k_, v_,
                                                   causal=causal),
            q, k, v)
        return vjp(g)
    t_q, t_k = q.shape[-2], k.shape[-2]
    # the backward kernels keep four (q_tile, block_k) f32 values live
    # at once (s, p, dp, ds) — cap q_tile at 512 so they fit the ~16 MB
    # scoped VMEM budget even when the forward ran at 1024
    qt = _fit_tile(t_q, min(q_tile, 512))
    bk = _fit_tile(t_k, block_k)
    dq, dk, dv = _flash_backward(
        q.reshape(-1, t_q, q.shape[-1]), k.reshape(-1, t_k, k.shape[-1]),
        v.reshape(-1, t_k, v.shape[-1]), out3,
        lse, g.reshape(-1, t_q, q.shape[-1]), causal, qt, bk, interpret)
    return dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape)


flash_attention.defvjp(_fwd, _bwd)
