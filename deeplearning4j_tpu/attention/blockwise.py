"""Blockwise (flash-style) attention in pure JAX.

The online-softmax recurrence: carry (acc, row_max, row_sum) over KV
blocks; each block contributes exp(S - new_max) rescaled history. This is
the memory-efficient form XLA compiles into a scan whose working set is
one (Tq, block) tile instead of the full (Tq, Tk) score matrix.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def naive_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Reference O(T^2)-memory attention (for tests and tiny inputs).
    Shapes: q (..., Tq, d), k/v (..., Tk, d)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d)
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v)


@partial(jax.jit, static_argnames=("causal", "block_size", "q_offset",
                                   "k_offset", "return_lse"))
def blockwise_attention(q, k, v, causal: bool = False,
                        block_size: int = 512,
                        q_offset: Optional[int] = None, k_offset: int = 0,
                        return_lse: bool = False):
    """Online-softmax attention over KV blocks.

    q: (..., Tq, d); k, v: (..., Tk, d). `q_offset`/`k_offset` are the
    global positions of the first query/key row, for callers passing
    sequence shards. Default alignment is BOTTOM-RIGHT (query i attends
    keys up to i + Tk - Tq — the KV-cache decode convention, matching
    `naive_attention`); pass q_offset explicitly for other geometries.
    Fully-masked query rows output zeros.

    `return_lse=True` additionally returns the per-row log-sum-exp of
    the scaled scores (natural log) — fully-masked rows get the +1e30
    sentinel the Pallas kernel emits — keeping the O(block) working set
    (the lse is read off the online-softmax carry, no score matrix).
    """
    orig_dtype = q.dtype
    q = q.astype(jnp.float32)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    d = q.shape[-1]
    tq, tk = q.shape[-2], k.shape[-2]
    scale = 1.0 / jnp.sqrt(d)
    if q_offset is None:
        # bottom-right causal alignment (naive_attention's tril(k=tk-tq))
        q_offset = k_offset + tk - tq
    block = min(block_size, tk)
    n_blocks = (tk + block - 1) // block
    pad = n_blocks * block - tk
    if pad:
        kp = jnp.pad(k, [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)])
        vp = jnp.pad(v, [(0, 0)] * (v.ndim - 2) + [(0, pad), (0, 0)])
    else:
        kp, vp = k, v
    # (n_blocks, ..., block, d) leading scan axis
    kb = jnp.moveaxis(
        kp.reshape(*k.shape[:-2], n_blocks, block, d), -3, 0)
    vb = jnp.moveaxis(
        vp.reshape(*v.shape[:-2], n_blocks, block, d), -3, 0)

    q_pos = q_offset + jnp.arange(tq)

    def body(carry, inputs):
        acc, m, s = carry
        kb_i, vb_i, blk = inputs
        scores = jnp.einsum("...qd,...kd->...qk", q, kb_i) * scale
        k_pos = k_offset + blk * block + jnp.arange(block)
        valid = (k_pos < k_offset + tk)
        if causal:
            valid = valid[None, :] & (k_pos[None, :] <= q_pos[:, None])
        else:
            valid = jnp.broadcast_to(valid[None, :],
                                     scores.shape[-2:])
        scores = jnp.where(valid, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        # explicit valid multiply: when a row is FULLY masked, m_new stays
        # at the NEG_INF init and exp(scores - m_new) would be 1, silently
        # attending to every key — the mask zeroes those rows instead
        p = jnp.exp(scores - m_new[..., None]) * valid.astype(jnp.float32)
        s_new = s * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "...qk,...kd->...qd", p, vb_i)
        return (acc_new, m_new, s_new), None

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    s0 = jnp.zeros(q.shape[:-1], jnp.float32)
    (acc, m, s), _ = lax.scan(
        body, (acc0, m0, s0), (kb, vb, jnp.arange(n_blocks)))
    out = acc / jnp.maximum(s, 1e-30)[..., None]
    if not return_lse:
        return out.astype(orig_dtype)
    lse = jnp.where(s > 0.0, m + jnp.log(jnp.maximum(s, 1e-30)),
                    jnp.float32(-NEG_INF))
    return out.astype(orig_dtype), lse
