"""Ring attention: sequence-parallel attention over a mesh axis.

Each device holds one sequence shard of Q, K, V. K/V shards rotate
around the ring via `lax.ppermute` (nearest-neighbor ICI exchange —
bandwidth-optimal, overlappable); every device keeps the online-softmax
running state for ITS queries and folds in each visiting K/V block.
After n_devices steps every query has attended to every key. Causal
masking uses global offsets derived from the device's ring position, so
a causal ring skips nothing but masks exactly.

This is the TPU-native equivalent of Ring Attention (Liu et al.) /
context parallelism: sequence length scales linearly with the number of
devices at constant per-device memory.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from deeplearning4j_tpu.attention.blockwise import NEG_INF


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          n_dev: int):
    """Per-device body (inside shard_map). q/k/v: (..., T_local, d).
    `n_dev` is the ring size, passed statically from the mesh (lax has no
    stable in-trace axis-size query across the jax versions we span)."""
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(d)
    orig_dtype = q.dtype
    q32 = q.astype(jnp.float32)

    q_pos = my_idx * t_local + jnp.arange(t_local)

    def accumulate(acc, m, s, k_cur, v_cur, step):
        src_idx = (my_idx - step) % n_dev  # whose shard we hold this step
        scores = jnp.einsum(
            "...qd,...kd->...qk", q32, k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = src_idx * t_local + jnp.arange(t_local)
            mask = k_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(mask, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # same sentinel guards as the flash merge below: a row that has
        # seen only masked keys keeps m == m_new == NEG_INF, where the
        # unguarded exp()s read as 1 — correct today only because step 0
        # folds the (never fully masked) diagonal shard first
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            p = p * mask.astype(jnp.float32)
        s_new = s * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "...qk,...kd->...qd", p, v_cur.astype(jnp.float32))
        return acc_new, m_new, s_new

    def fold(carry, step):
        acc, m, s, k_cur, v_cur = carry
        acc, m, s = accumulate(acc, m, s, k_cur, v_cur, step)
        # rotate K/V to the next device (ring neighbor exchange over ICI)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, s, k_next, v_next), None

    # constant-initialized carries must carry the same device-varying axes
    # as the scanned k/v (jax vma rules). Deriving them from q32 inherits
    # the right axis set whatever the in_specs shard over (sp alone, or
    # dp x sp when batch_axis is set); XLA folds the dummy arithmetic.
    acc0 = q32 * 0.0
    row = jnp.sum(q32, axis=-1) * 0.0
    m0 = row + NEG_INF
    s0 = row
    # n_dev - 1 fold+rotate steps, then the LAST visiting shard is
    # consumed without rotating it onward — the final ppermute's output
    # was a discarded scan carry (one wasted shard-sized ICI exchange
    # of both K and V per call, plus its transpose under grad)
    (acc, m, s, k_last, v_last), _ = lax.scan(
        fold, (acc0, m0, s0, k, v), jnp.arange(n_dev - 1))
    acc, m, s = accumulate(acc, m, s, k_last, v_last,
                           jnp.asarray(n_dev - 1))
    out = acc / jnp.maximum(s, 1e-30)[..., None]
    return out.astype(orig_dtype)


def _ring_attention_local_flash(q, k, v, axis_name: str, causal: bool,
                                interpret: bool, n_dev: int):
    """Per-device ring body with the Pallas flash kernel computing each
    visiting shard's local attention on the MXU (bf16 operands, f32
    state), merged across ring steps in log-space via the kernel's
    saved per-row lse:
        m' = max(m, lse_i); acc' = acc·e^(m-m') + out_i·e^(lse_i-m');
        s' = s·e^(m-m') + e^(lse_i-m');   out = acc/s.
    Visiting shards entirely in the causal past take the mask-free
    kernel; the self shard takes the causal kernel; future shards
    contribute nothing (their branch returns the -inf lse identity) —
    the same visible/diagonal/skip trichotomy the kernel applies to its
    own KV blocks, lifted to ring-shard granularity. Gradients flow
    through the joint (out, lse) custom vjp (the lse cotangent is a dd
    shift in the backward kernels — flash_pallas.py)."""
    from deeplearning4j_tpu.attention.flash_pallas import (
        flash_attention_with_lse)

    my_idx = lax.axis_index(axis_name)
    orig_dtype = q.dtype

    def local(k_cur, v_cur, is_causal):
        out, lse = flash_attention_with_lse(
            q, k_cur, v_cur, is_causal, interpret=interpret)
        return out.astype(jnp.float32), lse

    def accumulate(acc, m, s, k_cur, v_cur, step):
        src_idx = (my_idx - step) % n_dev

        def past(_):      # src < my: every key visible, mask-free kernel
            return local(k_cur, v_cur, False)

        def diag(_):      # src == my: standard causal within the shard
            return local(k_cur, v_cur, True)

        def future(_):    # src > my: fully masked — the merge identity
            z = jnp.zeros(q.shape, jnp.float32)
            return z, jnp.full(q.shape[:-1], NEG_INF, jnp.float32)

        if causal:
            out_i, lse_i = lax.cond(
                src_idx == my_idx, diag,
                lambda _: lax.cond(src_idx < my_idx, past, future, None),
                None)
        else:
            out_i, lse_i = past(None)
        m_new = jnp.maximum(m, lse_i)
        # explicit sentinel guards: a fully-masked shard's lse identity
        # (-1e30) merged while the carry m is still at its -1e30 init
        # would give exp(0) = 1, silently inflating the denominator.
        # Folding the diagonal shard first happens to avoid that, but
        # correctness must not depend on fold order — map the sentinel
        # to an exact 0 contribution on both sides of the merge.
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(lse_i <= NEG_INF / 2, 0.0, jnp.exp(lse_i - m_new))
        return (acc * alpha[..., None] + out_i * beta[..., None],
                m_new, s * alpha + beta)

    def fold(carry, step):
        acc, m, s, k_cur, v_cur = carry
        acc, m, s = accumulate(acc, m, s, k_cur, v_cur, step)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        return (acc, m, s,
                lax.ppermute(k_cur, axis_name, perm),
                lax.ppermute(v_cur, axis_name, perm)), None

    acc0 = q.astype(jnp.float32) * 0.0
    row = jnp.sum(q.astype(jnp.float32), axis=-1) * 0.0
    m0 = row + NEG_INF
    s0 = row
    # as in the einsum body: the last shard is consumed un-rotated
    (acc, m, s, k_last, v_last), _ = lax.scan(
        fold, (acc0, m0, s0, k, v), jnp.arange(n_dev - 1))
    acc, m, s = accumulate(acc, m, s, k_last, v_last,
                           jnp.asarray(n_dev - 1))
    out = acc / jnp.maximum(s, 1e-30)[..., None]
    return out.astype(orig_dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, batch_axis: Optional[str] = None,
                   local: str = "einsum", interpret: bool = False):
    """Full attention with Q/K/V sequence-sharded over `axis`.

    q, k, v: (batch, T, d) global arrays (T divisible by the axis size).
    Returns (batch, T, d), sequence-sharded the same way. Each ring step
    processes one visiting shard (per-device shards are already
    block-sized — the ring IS the blocking).

    `local` selects the per-step local-attention engine: 'einsum' (f32
    einsums + explicit online softmax — runs anywhere) or 'flash' (the
    Pallas flash kernel per visiting shard with log-space lse merging —
    the MXU path for real TPU pods; set interpret=True off-TPU).

    `batch_axis` additionally shards the batch dimension over a second
    mesh axis — the dp×sp composition (each data-parallel replica group
    runs its own ring over the `axis` dimension of the mesh).
    """
    n_dev = mesh.shape[axis]
    t = q.shape[-2]
    if t % n_dev:
        raise ValueError(f"sequence length {t} not divisible by mesh "
                         f"axis {axis!r} size {n_dev}")
    if batch_axis is not None and q.shape[0] % mesh.shape[batch_axis]:
        raise ValueError(f"batch {q.shape[0]} not divisible by mesh "
                         f"axis {batch_axis!r} size {mesh.shape[batch_axis]}")
    if local == "flash":
        body = partial(_ring_attention_local_flash, axis_name=axis,
                       causal=causal, interpret=interpret, n_dev=n_dev)
    elif local == "einsum":
        body = partial(_ring_attention_local, axis_name=axis,
                       causal=causal, n_dev=n_dev)
    else:
        raise ValueError(f"unknown local engine {local!r}; "
                         "expected 'einsum' or 'flash'")

    spec = P(batch_axis, axis, None)
    kw = {}
    if local == "flash":
        # pallas_call's out_shape structs carry no vma annotations, so
        # the new shard_map's varying-axes checker can't type them —
        # use its escape hatch (check_vma; check_rep on older jax)
        import inspect
        params = inspect.signature(_shard_map).parameters
        if "check_vma" in params:
            kw["check_vma"] = False
        elif "check_rep" in params:  # pre-rename jax
            kw["check_rep"] = False
    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * 3,
        out_specs=spec,
        **kw,
    )
    with mesh:
        return fn(q, k, v)
