"""Paged-attention decode as a Pallas TPU kernel.

`paged_decode_step` (serving/paged_kv.py) historically gathered every
slot's page list into a dense `(S, H, window, hd)` K/V window each
step — per-step HBM traffic scaling with the page-table RESERVATION
(`S × max_len`), not the tokens actually written. This kernel streams
pages straight from the pool instead (the PagedAttention design,
PAPERS.md arXiv:2603.09555, on the repo's kernel-with-interpret
portability pattern from `attention/flash_pallas.py`):

- grid `(S, P)`: one slot per row, one page-table column per step. The
  page table and per-slot lengths ride `PrefetchScalarGridSpec` scalar
  prefetch, so the K/V BlockSpec index map picks the PHYSICAL page
  (`pt[s, j]`) for each grid step — the pool is the kernel operand and
  no dense window is ever materialized;
- online softmax across a slot's pages: f32 scratch (acc, m, s) carried
  over the sequential page dimension, base-2 state (`exp2`, scores
  prescaled by log2(e)/sqrt(hd)) exactly like the flash kernels;
- pages past a slot's written frontier (`j * page_size > pos`) are
  skipped with `pl.when` — no MXU work, and because unallocated page
  table entries all hold the trash index, Pallas's pipeline skips even
  the re-fetch (consecutive grid steps with identical block indices);
- lanes past the cursor inside the frontier page are masked to NEG_INF
  (underflow to exactly 0), matching the gather path's masked softmax,
  so parity with `kernel="gather"` holds at 1e-5 (tests pin it under
  ragged membership, CoW-shared pages, and the max_len window edge).

`resolve_decode_kernel` is the lane selector behind the
`kernel="pallas"|"gather"|"auto"` knob (`DecodeLoop`, `engine`,
`cli serve`): `auto` takes the kernel only on TPU inside the calibrated
envelope and NEVER silently runs interpret mode off-TPU (the
`flash_pallas` group-gate precedent); explicit `pallas` off-TPU is an
error unless `cfg.interpret` is set (the CPU tier-1 test lane).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.flash_pallas import (LOG2E, NEG_INF,
                                                       _tpu_compiler_params)

__all__ = ["paged_attention", "resolve_decode_kernel", "DECODE_KERNELS"]

DECODE_KERNELS = ("auto", "pallas", "gather")


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, s_ref, *, page_size: int):
    """One (slot, page) grid step. `pt_ref`/`len_ref` are the
    scalar-prefetch operands (the same arrays the BlockSpec index maps
    read); K/V refs already hold the PHYSICAL page the index map
    selected for this step."""
    from jax.experimental import pallas as pl

    si = pl.program_id(0)
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    pos = len_ref[si]   # this slot's cursor: positions [0, pos] visible

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        s_ref[...] = jnp.zeros_like(s_ref)

    # pages wholly past the written frontier contribute exactly 0 in the
    # gather path (every lane masked): skip them here — page 0 always
    # computes (pos >= 0), so the softmax sum is never empty
    @pl.when(j * page_size <= pos)
    def _tile():
        q = q_ref[0]          # (H, hd)
        k = k_ref[0]          # (H, ps, hd)
        v = v_ref[0]
        hd = q.shape[-1]
        # base-2 softmax state, scores prescaled by log2(e)/sqrt(hd):
        # the transcendental is a bare exp2 (flash_pallas._kernel)
        scale2 = jnp.float32(LOG2E) / jnp.float32(hd) ** 0.5
        scores = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale2   # (H, ps)
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        mask = k_pos <= pos   # current token at `pos` IS visible
        scores = jnp.where(mask, scores, NEG_INF)
        m_prev, s_prev = m_ref[...], s_ref[...]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(scores - m_new)
        p = jnp.where(mask, p, 0.0)
        m_ref[...] = m_new
        s_ref[...] = s_prev * alpha + p.sum(axis=-1, keepdims=True)
        # P in V's storage dtype for the MXU dot, f32 accumulation —
        # same rounding story as the flash forward
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_j - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(s_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, page_table, lengths, *,
                    interpret: bool = False):
    """Single-token paged attention over the block pool.

    q: (S, H, hd) — one decode query row per slot (the token being
    written this step). k_pool/v_pool: (n_pages + 1, H, page_size, hd)
    block pools, last page = trash. page_table: (S, P) int32 pool
    indices (trash-filled past each slot's allocation). lengths: (S,)
    int32 cursors — positions [0, lengths[s]] are attended (the
    incoming token's K/V must already be scattered at its cursor,
    exactly as `paged_decode_step` orders writes before attention).

    Returns (S, H, hd) in q.dtype. page_table/lengths are traced
    values: membership changes never recompile (the
    `decode_step_programs() == 1` invariant)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, h, hd = q.shape
    ps = k_pool.shape[2]
    n_j = page_table.shape[1]
    kv_spec = pl.BlockSpec((1, h, ps, hd),
                           lambda si, j, pt, ln: (pt[si, j], 0, 0, 0),
                           memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, n_j),
        in_specs=[
            pl.BlockSpec((1, h, hd), lambda si, j, pt, ln: (si, 0, 0),
                         memory_space=pltpu.VMEM),
            kv_spec,
            kv_spec,
        ],
        out_specs=pl.BlockSpec((1, h, hd),
                               lambda si, j, pt, ln: (si, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((h, hd), jnp.float32),   # acc
            pltpu.VMEM((h, 1), jnp.float32),    # running max (base-2)
            pltpu.VMEM((h, 1), jnp.float32),    # running sum
        ])
    return pl.pallas_call(
        partial(_decode_kernel, page_size=ps),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, hd), q.dtype),
        # slots are independent (scratch init/finalize is per-row);
        # only the page sweep carries the online-softmax state
        compiler_params=_tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pool, v_pool)


def resolve_decode_kernel(kernel: str, cfg, page_size: int) -> str:
    """Resolve the `kernel="pallas"|"gather"|"auto"` knob to the lane
    `paged_decode_step` actually runs — ONCE, at loop construction, so
    the decode step stays one compiled program.

    - "gather": always the dense-gather path.
    - "pallas": the kernel; off-TPU this raises unless `cfg.interpret`
      is set (tests run the kernel code path through the interpreter —
      production must never fall into that silently).
    - "auto": the kernel on TPU inside the calibrated envelope
      (hd <= 128, <= 4-byte KV dtype, page_size >= 8 — lanes/sublane
      padding stays bounded); everything else takes the gather path.
      Off-TPU auto is ALWAYS gather, interpret or not: interpret mode
      is a test lane, not a production fallback."""
    if kernel not in DECODE_KERNELS:
        raise ValueError(
            f"kernel must be one of {DECODE_KERNELS}, got {kernel!r}")
    on_tpu = jax.default_backend() == "tpu"
    if kernel == "gather":
        return "gather"
    if kernel == "pallas":
        if not on_tpu and not getattr(cfg, "interpret", False):
            raise ValueError(
                "kernel='pallas' needs a TPU backend; off-TPU the "
                "kernel only runs under interpret mode (set "
                "cfg.interpret=True in tests) — use kernel='gather' "
                "or 'auto' instead")
        return "pallas"
    # auto
    if not on_tpu:
        return "gather"
    hd = cfg.d_model // cfg.n_heads
    itemsize = jnp.dtype(cfg.dtype).itemsize
    if hd > 128 or itemsize > 4 or page_size < 8:
        return "gather"
    return "pallas"
