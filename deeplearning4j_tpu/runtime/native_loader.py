"""ctypes loader for the native runtime library, with numpy fallbacks.

Builds `libdl4j_native.so` from runtime/native/native.cpp on first use
(g++ -O3 -shared -fPIC; ~1 s, cached next to the source). The CPython
boundary is ctypes (pybind11 is not in the image — SURVEY environment
notes), with buffer ownership handed to numpy via explicit free.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "native.cpp")
_SO = os.path.join(_NATIVE_DIR, "libdl4j_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO,
           "-pthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("native build failed (%s); using numpy fallbacks", e)
        return False


def _load():
    """Build (if needed) and load the shared library; None on failure."""
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native load failed (%s)", e)
            _build_failed = True
            return None
        lib.dl4j_idx_read.restype = ctypes.c_int
        lib.dl4j_idx_read.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int)]
        lib.dl4j_csv_read.restype = ctypes.c_int
        lib.dl4j_csv_read.argtypes = [
            ctypes.c_char_p, ctypes.c_char,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_buffer_free.argtypes = [ctypes.c_void_p]
        lib.dl4j_queue_create.restype = ctypes.c_void_p
        lib.dl4j_queue_create.argtypes = [ctypes.c_int64]
        lib.dl4j_queue_push.restype = ctypes.c_int
        lib.dl4j_queue_push.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint8),
                                        ctypes.c_int64]
        lib.dl4j_queue_pop.restype = ctypes.c_int64
        lib.dl4j_queue_pop.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.dl4j_queue_size.restype = ctypes.c_int64
        lib.dl4j_queue_size.argtypes = [ctypes.c_void_p]
        lib.dl4j_queue_close.argtypes = [ctypes.c_void_p]
        lib.dl4j_queue_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------- IDX
def read_idx(path: str) -> np.ndarray:
    """Read an IDX file into a uint8 ndarray (native; numpy fallback)."""
    lib = _load()
    if lib is None:
        return _read_idx_numpy(path)
    data = ctypes.POINTER(ctypes.c_uint8)()
    dims = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int()
    rc = lib.dl4j_idx_read(path.encode(), ctypes.byref(data), dims,
                           ctypes.byref(ndim))
    if rc != 0:
        raise ValueError(f"IDX read failed for {path} (code {rc})")
    shape = tuple(int(dims[i]) for i in range(ndim.value))
    n = int(np.prod(shape))
    try:
        arr = np.ctypeslib.as_array(data, shape=(n,)).reshape(shape).copy()
    finally:
        lib.dl4j_buffer_free(data)
    return arr


def _read_idx_numpy(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        zero1, zero2, dtype, ndim = struct.unpack(">BBBB", f.read(4))
        if zero1 or zero2 or dtype != 0x08:
            raise ValueError(f"Bad IDX header in {path}")
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape).copy()


# ------------------------------------------------------------------- CSV
def read_csv(path: str, delimiter: str = ",") -> np.ndarray:
    """Numeric CSV -> float32 matrix (native; numpy fallback)."""
    lib = _load()
    if lib is None:
        # comments=None: the native parser rejects '#' lines as unparsable,
        # so the fallback must too — behavior must not depend on whether
        # the .so loaded.
        return np.loadtxt(path, delimiter=delimiter,
                          dtype=np.float32, ndmin=2, comments=None)
    data = ctypes.POINTER(ctypes.c_float)()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.dl4j_csv_read(path.encode(), delimiter.encode(),
                           ctypes.byref(data), ctypes.byref(rows),
                           ctypes.byref(cols))
    if rc != 0:
        raise ValueError(f"CSV read failed for {path} (code {rc})")
    try:
        arr = np.ctypeslib.as_array(
            data, shape=(rows.value * cols.value,)).reshape(
                rows.value, cols.value).copy()
    finally:
        lib.dl4j_buffer_free(data)
    return arr


# ---------------------------------------------------------- batch queue
class BatchQueue:
    """Bounded producer/consumer queue over the native ring (double
    buffering between host batch assembly and the device step). Items are
    float32 ndarrays; shape travels in a small header. Pure-Python
    fallback uses queue.Queue."""

    def __init__(self, capacity: int = 4):
        self._lib = _load()
        if self._lib is not None:
            self._handle = self._lib.dl4j_queue_create(capacity)
            self._py = None
        else:
            import queue
            self._handle = None
            self._py = queue.Queue(maxsize=capacity)
        self._closed = False

    @staticmethod
    def _pack(arr: np.ndarray) -> np.ndarray:
        arr = np.ascontiguousarray(arr, np.float32)
        if arr.ndim > 4:
            raise ValueError(
                f"BatchQueue supports ndim <= 4, got ndim={arr.ndim} "
                "(the 5-int64 wire header carries at most 4 dims)")
        header = np.array([arr.ndim, *arr.shape, *([0] * (4 - arr.ndim))],
                          np.int64)
        return np.concatenate([header.view(np.uint8),
                               arr.ravel().view(np.uint8)])

    @staticmethod
    def _unpack(buf: np.ndarray) -> np.ndarray:
        header = buf[:40].view(np.int64)
        ndim = int(header[0])
        shape = tuple(int(d) for d in header[1:1 + ndim])
        return buf[40:].view(np.float32).reshape(shape).copy()

    def push(self, arr: np.ndarray) -> bool:
        """Blocking; returns False if the queue is closed."""
        if self._py is not None:
            if self._closed:
                return False
            self._py.put(np.asarray(arr, np.float32))
            return True
        packed = self._pack(arr)
        ptr = packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        return self._lib.dl4j_queue_push(self._handle, ptr,
                                         packed.size) == 0

    def pop(self) -> Optional[np.ndarray]:
        """Blocking; None when closed and drained."""
        if self._py is not None:
            import queue
            while True:
                try:
                    return self._py.get(timeout=0.05)
                except queue.Empty:
                    if self._closed:
                        return None
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.dl4j_queue_pop(self._handle, ctypes.byref(data))
        if n < 0:
            return None
        try:
            buf = np.ctypeslib.as_array(data, shape=(n,)).copy()
        finally:
            self._lib.dl4j_buffer_free(data)
        return self._unpack(buf)

    def size(self) -> int:
        if self._py is not None:
            return self._py.qsize()
        return int(self._lib.dl4j_queue_size(self._handle))

    def close(self) -> None:
        self._closed = True
        if self._py is None:
            self._lib.dl4j_queue_close(self._handle)

    def __del__(self):
        try:
            if getattr(self, "_py", True) is None and self._handle:
                self._lib.dl4j_queue_destroy(self._handle)
                self._handle = None
        except Exception:
            pass
