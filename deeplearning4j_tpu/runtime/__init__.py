"""Host-side native runtime (C++ via ctypes).

Parity: the reference's native layer — ND4J's jblas/JNI backend and
Canova's readers (SURVEY §2 [NATIVE-EQ]). TPU-native split: device math
is XLA's; the native library owns host-side IO (IDX/CSV decoding) and
the bounded producer/consumer queue used for input double-buffering.
Every entry point has a pure-numpy fallback so the framework works
without a toolchain; the native path is used when the shared library
builds (g++, baked into the image).
"""

from deeplearning4j_tpu.runtime.native_loader import (  # noqa: F401
    BatchQueue,
    native_available,
    read_csv,
    read_idx,
)
