// Native host runtime for deeplearning4j_tpu.
//
// Parity: the reference system's native layer is external — ND4J's
// jblas/JNI BLAS and Canova's record readers (SURVEY §2 [NATIVE-EQ]).
// On TPU the device math belongs to XLA, so the native layer owns what
// actually runs on the HOST: dataset decoding (IDX/CSV) and the bounded
// producer/consumer batch queue that double-buffers input batches ahead
// of the device step (the reference's DataSetIterator prefetch role).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in image).
// Build: g++ -O3 -shared -fPIC -std=c++17 native.cpp -o libdl4j_native.so

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- IDX IO
// Reads an IDX file (magic 0x0803 images / 0x0801 labels, big-endian
// header) into a malloc'd byte buffer. Returns 0 on success.
// dims_out must hold 4 int64 slots; ndim_out receives the rank.
int dl4j_idx_read(const char* path, uint8_t** data_out, int64_t* dims_out,
                  int* ndim_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  uint8_t header[4];
  if (std::fread(header, 1, 4, f) != 4) { std::fclose(f); return -2; }
  if (header[0] != 0 || header[1] != 0) { std::fclose(f); return -3; }
  const int dtype = header[2];   // 0x08 = unsigned byte (only type used)
  const int ndim = header[3];
  if (dtype != 0x08 || ndim < 1 || ndim > 4) { std::fclose(f); return -3; }
  int64_t total = 1;
  for (int i = 0; i < ndim; i++) {
    uint8_t b[4];
    if (std::fread(b, 1, 4, f) != 4) { std::fclose(f); return -2; }
    int64_t d = (int64_t(b[0]) << 24) | (int64_t(b[1]) << 16) |
                (int64_t(b[2]) << 8) | int64_t(b[3]);
    dims_out[i] = d;
    total *= d;
  }
  *ndim_out = ndim;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(total));
  if (!buf) { std::fclose(f); return -4; }
  const int64_t got = static_cast<int64_t>(std::fread(buf, 1, total, f));
  std::fclose(f);
  if (got != total) { std::free(buf); return -5; }
  *data_out = buf;
  return 0;
}

void dl4j_buffer_free(void* p) { std::free(p); }

// ---------------------------------------------------------------- CSV IO
// Parses a numeric CSV into a malloc'd float32 row-major matrix.
// Returns 0 on success; rows/cols via out params.
int dl4j_csv_read(const char* path, char delimiter, float** data_out,
                  int64_t* rows_out, int64_t* cols_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  const long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> text(static_cast<size_t>(size) + 1);
  if (std::fread(text.data(), 1, size, f) != static_cast<size_t>(size)) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);
  text[size] = '\0';

  std::vector<float> values;
  values.reserve(1024);
  int64_t rows = 0, cols = -1, cur_cols = 0;
  const char* p = text.data();
  const char* end = text.data() + size;
  while (p < end) {
    char* next = nullptr;
    const float v = std::strtof(p, &next);
    if (next == p) {  // no parse
      if (*p == '\n') {
        if (cur_cols > 0) {
          if (cols < 0) cols = cur_cols;
          else if (cols != cur_cols) return -3;  // ragged
          rows++;
          cur_cols = 0;
        }
        p++;
        continue;
      }
      if (*p == '\r' || *p == ' ' || *p == '\t' || *p == delimiter) {
        p++;
        continue;
      }
      return -6;  // unparsable text (e.g. header row) — match numpy, which
                  // raises on the same input rather than dropping it
    }
    values.push_back(v);
    cur_cols++;
    p = next;
    while (p < end && (*p == delimiter || *p == ' ' || *p == '\r')) p++;
    if (p < end && *p == '\n') {
      if (cols < 0) cols = cur_cols;
      else if (cols != cur_cols) return -3;
      rows++;
      cur_cols = 0;
      p++;
    }
  }
  if (cur_cols > 0) {  // final line without newline
    if (cols < 0) cols = cur_cols;
    else if (cols != cur_cols) return -3;
    rows++;
  }
  if (rows == 0 || cols <= 0) return -4;
  float* buf = static_cast<float*>(std::malloc(sizeof(float) * rows * cols));
  if (!buf) return -5;
  std::memcpy(buf, values.data(), sizeof(float) * rows * cols);
  *data_out = buf;
  *rows_out = rows;
  *cols_out = cols;
  return 0;
}

// -------------------------------------------------- bounded batch queue
// Producer/consumer ring for host-side double buffering: the Python (or
// future C++) producer decodes/assembles batches while the device step
// consumes the previous one. Blocking push/pop with shutdown.
struct Queue {
  std::deque<std::pair<uint8_t*, int64_t>> items;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  size_t capacity;
  bool closed = false;
};

void* dl4j_queue_create(int64_t capacity) {
  Queue* q = new Queue();
  q->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 2;
  return q;
}

// Copies `len` bytes; blocks while full. Returns 0, or -1 if closed.
int dl4j_queue_push(void* handle, const uint8_t* data, int64_t len) {
  Queue* q = static_cast<Queue*>(handle);
  std::unique_lock<std::mutex> lock(q->mu);
  q->not_full.wait(lock, [q] {
    return q->closed || q->items.size() < q->capacity;
  });
  if (q->closed) return -1;
  uint8_t* copy = static_cast<uint8_t*>(std::malloc(len));
  if (!copy) return -2;
  std::memcpy(copy, data, len);
  q->items.emplace_back(copy, len);
  q->not_empty.notify_one();
  return 0;
}

// Blocks while empty. Returns item length >= 0 (caller frees via
// dl4j_buffer_free), or -1 when closed AND drained.
int64_t dl4j_queue_pop(void* handle, uint8_t** data_out) {
  Queue* q = static_cast<Queue*>(handle);
  std::unique_lock<std::mutex> lock(q->mu);
  q->not_empty.wait(lock, [q] { return q->closed || !q->items.empty(); });
  if (q->items.empty()) return -1;  // closed + drained
  auto item = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  *data_out = item.first;
  return item.second;
}

int64_t dl4j_queue_size(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  std::lock_guard<std::mutex> lock(q->mu);
  return static_cast<int64_t>(q->items.size());
}

// Close: producers stop, consumers drain then get -1.
void dl4j_queue_close(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  {
    std::lock_guard<std::mutex> lock(q->mu);
    q->closed = true;
  }
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

void dl4j_queue_destroy(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  {
    std::lock_guard<std::mutex> lock(q->mu);
    for (auto& item : q->items) std::free(item.first);
    q->items.clear();
    q->closed = true;
  }
  q->not_empty.notify_all();
  q->not_full.notify_all();
  delete q;
}

}  // extern "C"
