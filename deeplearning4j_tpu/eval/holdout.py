"""One-shot held-out evaluation of a checkpoint — the eval gate's meat.

`evaluate_checkpoint(model, data)` loads any checkpoint the serving
stack can load (sharded directory, single-file npz, or a conf .json for
a fresh net), runs the held-out CSV through `Evaluation`, and returns
the metrics dict both consumers speak:

- `cli eval -m <checkpoint> --data <csv> --json` prints it (the same
  {"f1", "accuracy", "precision", "recall"} shape `cli test` emits, plus
  the checkpoint identity), and
- the deployment controller's eval gate (deploy/controller.py) compares
  it against its absolute threshold and the current champion's score
  before offering a candidate to the fleet (docs/PIPELINE.md).

`evaluate_via_fleet(url, data)` is the live twin: it scores whatever a
serving endpoint (fleet router or single replica) CURRENTLY serves by
driving the held-out set through ``POST /predict`` — on the BATCH SLO
tier (docs/SERVING.md "Priority tiers"), because bulk scoring is
offline work that must never compete with interactive admission: it
sheds first at the batch lane's lower high-water mark and honors the
tier-aware ``Retry-After`` on a 503 before retrying. The deployment
controller uses it to refresh the champion's baseline from the live
fleet before the regression comparison (`eval_via_fleet=`).

Held-out CSV shape matches the rest of the CLI: one row per example,
features then the label column(s) — an integer class column when
`label_columns == 1` (one-hot expanded against the MODEL's output
width, so a file missing the top class cannot shrink the label space).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.eval.evaluation import Evaluation

__all__ = ["evaluate_checkpoint", "evaluate_via_fleet",
           "load_holdout_csv"]


def load_holdout_csv(path: str, label_columns: int = 1,
                     n_classes: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(features, one-hot labels) from a labelled CSV. Raises on a
    label-free file — a gate with no labels cannot gate."""
    if label_columns < 1:
        raise ValueError("held-out evaluation needs label_columns >= 1")
    data = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    x = data[:, :-label_columns]
    y = data[:, -label_columns:]
    if label_columns == 1:  # integer class column -> one-hot
        labels = y.astype(int).ravel()
        classes = n_classes if n_classes else int(labels.max()) + 1
        if labels.max() >= classes:
            raise ValueError(
                f"label {labels.max()} out of range for model with "
                f"{classes} output classes")
        y = np.eye(classes, dtype=np.float32)[labels]
    return x, y


def _load_net(model: str, step: Optional[int] = None):
    """(net, checkpoint_step_or_None) for a sharded dir, npz file, or
    conf .json — the same dispatch the serving reload path uses."""
    if os.path.isdir(model):
        from deeplearning4j_tpu.checkpoint.restore import restore_network

        net, info = restore_network(model, step)
        return net, info.get("step", step)
    if model.endswith(".json"):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with open(model) as f:
            return MultiLayerNetwork.from_config_json(f.read()), None
    if step is not None:
        raise ValueError(
            f"step={step} was requested but {model!r} is a single-file "
            "checkpoint with no steps")
    from deeplearning4j_tpu.scaleout.checkpoint import load_checkpoint

    net, _ = load_checkpoint(model)
    return net, None


def evaluate_via_fleet(url: str, data: str, *,
                       label_columns: int = 1,
                       n_classes: Optional[int] = None,
                       batch_size: int = 64,
                       timeout: float = 120.0,
                       max_shed_retries: int = 8) -> dict:
    """Score the held-out CSV against a LIVE serving endpoint (fleet
    router or single replica) instead of loading weights locally —
    the metrics describe whatever the endpoint currently serves.

    Every request rides the BATCH SLO tier: the `X-Priority: batch`
    header (and a matching `"priority"` body field, for endpoints
    reached without the router) keeps bulk scoring out of the
    interactive lane. A 503 shed is honored, not fatal: the reply's
    `retry_after_ms` (derived from the batch lane's own backlog) is
    waited out — capped at 5s a beat, `max_shed_retries` beats total —
    before the chunk retries. Other HTTP errors raise RuntimeError
    (the caller decides whether that is an infra failure)."""
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.serving.errors import (PRIORITY_HEADER,
                                                   TIER_BATCH)

    x, y = load_holdout_csv(data, label_columns, n_classes)
    url = url.rstrip("/")
    start = time.perf_counter()
    outs = []
    sheds = 0
    for lo in range(0, x.shape[0], batch_size):
        body = json.dumps({
            "inputs": x[lo:lo + batch_size].tolist(),
            "priority": TIER_BATCH}).encode()
        while True:
            req = urllib.request.Request(
                url + "/predict", data=body,
                headers={"Content-Type": "application/json",
                         PRIORITY_HEADER: TIER_BATCH})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    payload = json.loads(r.read())
                break
            except urllib.error.HTTPError as e:
                raw = e.read()
                if e.code == 503 and sheds < max_shed_retries:
                    sheds += 1
                    try:
                        retry_ms = json.loads(raw).get(
                            "retry_after_ms", 1000)
                    except ValueError:
                        retry_ms = 1000
                    time.sleep(min(5.0, max(0.05, retry_ms / 1000.0)))
                    continue
                raise RuntimeError(
                    f"fleet eval: /predict answered {e.code}: "
                    f"{raw.decode(errors='replace')[:200]}") from e
        outs.append(np.asarray(payload["outputs"], dtype=np.float32))
    ev = Evaluation()
    ev.eval(y, np.concatenate(outs, axis=0))
    return {
        "f1": ev.f1(),
        "accuracy": ev.accuracy(),
        "precision": ev.precision(),
        "recall": ev.recall(),
        "n": int(x.shape[0]),
        "path": url,
        "step": None,
        "via": "fleet",
        "tier": TIER_BATCH,
        "shed_retries": sheds,
        "eval_seconds": round(time.perf_counter() - start, 6),
    }


def evaluate_checkpoint(model: str, data: str, *,
                        label_columns: int = 1,
                        step: Optional[int] = None) -> dict:
    """Evaluate `model` (checkpoint path) on the held-out CSV `data`.

    Returns {"f1", "accuracy", "precision", "recall", "n", "path",
    "step", "eval_seconds"} — step is the checkpoint's committed step
    when it has one (sharded dirs), else None.
    """
    start = time.perf_counter()
    net, ck_step = _load_net(model, step)
    try:
        n_out = net.conf.confs[-1].n_out or None
    except (AttributeError, IndexError):
        n_out = None
    x, y = load_holdout_csv(data, label_columns, n_out)
    ev = Evaluation()
    ev.eval(y, np.asarray(net.output(x)))
    return {
        "f1": ev.f1(),
        "accuracy": ev.accuracy(),
        "precision": ev.precision(),
        "recall": ev.recall(),
        "n": int(x.shape[0]),
        "path": model,
        "step": ck_step,
        "eval_seconds": round(time.perf_counter() - start, 6),
    }
