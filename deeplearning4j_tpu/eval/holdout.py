"""One-shot held-out evaluation of a checkpoint — the eval gate's meat.

`evaluate_checkpoint(model, data)` loads any checkpoint the serving
stack can load (sharded directory, single-file npz, or a conf .json for
a fresh net), runs the held-out CSV through `Evaluation`, and returns
the metrics dict both consumers speak:

- `cli eval -m <checkpoint> --data <csv> --json` prints it (the same
  {"f1", "accuracy", "precision", "recall"} shape `cli test` emits, plus
  the checkpoint identity), and
- the deployment controller's eval gate (deploy/controller.py) compares
  it against its absolute threshold and the current champion's score
  before offering a candidate to the fleet (docs/PIPELINE.md).

Held-out CSV shape matches the rest of the CLI: one row per example,
features then the label column(s) — an integer class column when
`label_columns == 1` (one-hot expanded against the MODEL's output
width, so a file missing the top class cannot shrink the label space).
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.eval.evaluation import Evaluation

__all__ = ["evaluate_checkpoint", "load_holdout_csv"]


def load_holdout_csv(path: str, label_columns: int = 1,
                     n_classes: Optional[int] = None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(features, one-hot labels) from a labelled CSV. Raises on a
    label-free file — a gate with no labels cannot gate."""
    if label_columns < 1:
        raise ValueError("held-out evaluation needs label_columns >= 1")
    data = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    x = data[:, :-label_columns]
    y = data[:, -label_columns:]
    if label_columns == 1:  # integer class column -> one-hot
        labels = y.astype(int).ravel()
        classes = n_classes if n_classes else int(labels.max()) + 1
        if labels.max() >= classes:
            raise ValueError(
                f"label {labels.max()} out of range for model with "
                f"{classes} output classes")
        y = np.eye(classes, dtype=np.float32)[labels]
    return x, y


def _load_net(model: str, step: Optional[int] = None):
    """(net, checkpoint_step_or_None) for a sharded dir, npz file, or
    conf .json — the same dispatch the serving reload path uses."""
    if os.path.isdir(model):
        from deeplearning4j_tpu.checkpoint.restore import restore_network

        net, info = restore_network(model, step)
        return net, info.get("step", step)
    if model.endswith(".json"):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with open(model) as f:
            return MultiLayerNetwork.from_config_json(f.read()), None
    if step is not None:
        raise ValueError(
            f"step={step} was requested but {model!r} is a single-file "
            "checkpoint with no steps")
    from deeplearning4j_tpu.scaleout.checkpoint import load_checkpoint

    net, _ = load_checkpoint(model)
    return net, None


def evaluate_checkpoint(model: str, data: str, *,
                        label_columns: int = 1,
                        step: Optional[int] = None) -> dict:
    """Evaluate `model` (checkpoint path) on the held-out CSV `data`.

    Returns {"f1", "accuracy", "precision", "recall", "n", "path",
    "step", "eval_seconds"} — step is the checkpoint's committed step
    when it has one (sharded dirs), else None.
    """
    start = time.perf_counter()
    net, ck_step = _load_net(model, step)
    try:
        n_out = net.conf.confs[-1].n_out or None
    except (AttributeError, IndexError):
        n_out = None
    x, y = load_holdout_csv(data, label_columns, n_out)
    ev = Evaluation()
    ev.eval(y, np.asarray(net.output(x)))
    return {
        "f1": ev.f1(),
        "accuracy": ev.accuracy(),
        "precision": ev.precision(),
        "recall": ev.recall(),
        "n": int(x.shape[0]),
        "path": model,
        "step": ck_step,
        "eval_seconds": round(time.perf_counter() - start, 6),
    }
