"""Confusion matrix (reference core/eval/ConfusionMatrix.java, 258 LoC).

Backed by a dense numpy counts matrix so whole batches accumulate in one
`np.add.at` scatter instead of a per-row Python loop.
"""

from __future__ import annotations

from typing import List

import numpy as np


class ConfusionMatrix:
    def __init__(self, classes: List[int]):
        self.classes = sorted(classes)
        self._index = {c: i for i, c in enumerate(self.classes)}
        n = len(self.classes)
        self._counts = np.zeros((n, n), np.int64)

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self._counts[self._index[actual], self._index[predicted]] += count

    def add_batch(self, actual, predicted) -> None:
        """Accumulate whole label vectors at once (vectorized scatter-add)."""
        cls = np.asarray(self.classes)

        def to_index(vals, name):
            vals = np.asarray(vals).ravel()
            idx = np.searchsorted(cls, vals)
            bad = (idx >= len(cls)) | (cls[np.minimum(idx, len(cls) - 1)]
                                       != vals)
            if bad.any():
                raise KeyError(
                    f"Unknown {name} label(s) {np.unique(vals[bad])!r}; "
                    f"classes are {self.classes}")
            return idx

        a = to_index(actual, "actual")
        p = to_index(predicted, "predicted")
        np.add.at(self._counts, (a, p), 1)

    def count(self, actual: int, predicted: int) -> int:
        return int(self._counts[self._index[actual], self._index[predicted]])

    def actual_total(self, actual: int) -> int:
        return int(self._counts[self._index[actual]].sum())

    def predicted_total(self, predicted: int) -> int:
        return int(self._counts[:, self._index[predicted]].sum())

    def total(self) -> int:
        return int(self._counts.sum())

    def __str__(self) -> str:
        header = "actual\\pred " + " ".join(f"{c:>6}" for c in self.classes)
        rows = [header]
        for a in self.classes:
            rows.append(f"{a:>11} " + " ".join(
                f"{self.count(a, p):>6}" for p in self.classes))
        return "\n".join(rows)
