"""Confusion matrix (reference core/eval/ConfusionMatrix.java, 258 LoC)."""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List


class ConfusionMatrix:
    def __init__(self, classes: List[int]):
        self.classes = sorted(classes)
        self.matrix: Dict[int, Dict[int, int]] = defaultdict(lambda: defaultdict(int))

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.matrix[actual][predicted] += count

    def count(self, actual: int, predicted: int) -> int:
        return self.matrix[actual][predicted]

    def actual_total(self, actual: int) -> int:
        return sum(self.matrix[actual].values())

    def predicted_total(self, predicted: int) -> int:
        return sum(row[predicted] for row in self.matrix.values())

    def total(self) -> int:
        return sum(self.actual_total(c) for c in self.classes)

    def __str__(self) -> str:
        header = "actual\\pred " + " ".join(f"{c:>6}" for c in self.classes)
        rows = [header]
        for a in self.classes:
            rows.append(f"{a:>11} " + " ".join(
                f"{self.count(a, p):>6}" for p in self.classes))
        return "\n".join(rows)
