from deeplearning4j_tpu.eval.evaluation import Evaluation  # noqa: F401
from deeplearning4j_tpu.eval.confusion import ConfusionMatrix  # noqa: F401
from deeplearning4j_tpu.eval.holdout import (  # noqa: F401
    evaluate_checkpoint,
    load_holdout_csv,
)
