"""Classification metrics from confusion counts.

Parity: reference core/eval/Evaluation.java — `eval(realOutcomes, guesses)`
(:46), `precision`/`recall`/`f1`/`accuracy` (:160-244), `stats()` (:97).
Inputs are one-hot (or probability) matrices like the reference's INDArray
outcome/guess pairs; device arrays are accepted and pulled to host once.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.eval.confusion import ConfusionMatrix


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None):
        self.num_classes = num_classes
        self.confusion: Optional[ConfusionMatrix] = None

    def eval(self, real_outcomes, guesses) -> None:
        """Accumulate a batch of (one-hot truth, predicted scores)."""
        truth = np.asarray(real_outcomes)
        guess = np.asarray(guesses)
        n_classes = self.num_classes or truth.shape[-1]
        if self.confusion is None:
            self.confusion = ConfusionMatrix(list(range(n_classes)))
        self.confusion.add_batch(truth.argmax(-1), guess.argmax(-1))

    # ------------------------------------------------------------ metrics
    def _tp(self, c: int) -> int:
        return self.confusion.count(c, c)

    def precision(self, c: Optional[int] = None) -> float:
        if c is not None:
            denom = self.confusion.predicted_total(c)
            return self._tp(c) / denom if denom else 0.0
        vals = [self.precision(c) for c in self.confusion.classes]
        return float(np.mean(vals))

    def recall(self, c: Optional[int] = None) -> float:
        if c is not None:
            denom = self.confusion.actual_total(c)
            return self._tp(c) / denom if denom else 0.0
        vals = [self.recall(c) for c in self.confusion.classes]
        return float(np.mean(vals))

    def f1(self, c: Optional[int] = None) -> float:
        p, r = self.precision(c), self.recall(c)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def accuracy(self) -> float:
        total = self.confusion.total()
        correct = sum(self._tp(c) for c in self.confusion.classes)
        return correct / total if total else 0.0

    def stats(self) -> str:
        """Human-readable summary (reference stats() :97)."""
        lines = ["==========================Scores=====================",
                 str(self.confusion),
                 f" Accuracy:  {self.accuracy():.4f}",
                 f" Precision: {self.precision():.4f}",
                 f" Recall:    {self.recall():.4f}",
                 f" F1 Score:  {self.f1():.4f}",
                 "====================================================="]
        return "\n".join(lines)
