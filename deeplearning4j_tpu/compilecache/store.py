"""Persistent compiled-program store: fingerprinted, crash-atomic, LRU.

The disk layer of the AOT warm-start subsystem (docs/WARMUP.md). A
`ProgramStore` owns one cache directory and keeps serialized XLA
executables (`jax.experimental.serialize_executable` payloads) in it,
one file per program key, under a RUNTIME FINGERPRINT subdirectory:

    <root>/v1/<fingerprint>/<key-digest>.xc

The fingerprint hashes jax/jaxlib versions, the backend platform, and
the device topology — a cache written by a different runtime is never
even looked at (stale entries can only produce wrong or unloadable
programs; quarantining by construction beats validating on load). On
open, any OTHER fingerprint's subtree is swept and counted as
`dl4j_compile_cache_evictions{reason="fingerprint"}`.

Entry format: a small header (magic + payload CRC32 + length) followed
by the pickled `(payload, in_tree, out_tree)` triple. Writes are
crash-atomic with the repo's one durability idiom (utils/statefile.py,
checkpoint/format.py): tmp write -> fsync -> `os.replace`. A reader
can therefore see only the previous entry or the new one; anything
else (external truncation, a torn copy of the directory) fails the CRC
and is deleted — skipped, never loaded (`reason="torn"`).

Size is bounded by an LRU byte budget: after each write the store
evicts oldest-read entries (mtime order; `get` touches mtime) until
under budget (`reason="lru"`).

Fault injection: chaos points `compile.cache_write` (op="write" before
the tmp write, op="rename" before the commit rename) and
`compile.cache_read` (before each entry read). Every failure path —
injected or real IO — DEGRADES: `put` returns False, `get` returns
None, and the caller compiles like the cache never existed. The cache
must never be able to take serving down.
"""

from __future__ import annotations

import binascii
import hashlib
import logging
import os
import struct
from typing import Dict, Optional

from deeplearning4j_tpu.testing import chaos

__all__ = ["ProgramStore", "runtime_fingerprint", "key_digest"]

log = logging.getLogger(__name__)

_MAGIC = b"DL4JXC1\n"
_HEADER = struct.Struct(">II")  # crc32, payload length
_LAYOUT = "v1"
_SUFFIX = ".xc"

#: default LRU byte budget (override per-store or via
#: DL4J_TPU_COMPILE_CACHE_BUDGET_MB)
DEFAULT_BUDGET_BYTES = 512 * 1024 * 1024
BUDGET_ENV = "DL4J_TPU_COMPILE_CACHE_BUDGET_MB"


def runtime_fingerprint() -> str:
    """Digest of everything that can invalidate a serialized executable:
    jax + jaxlib versions, backend platform, device kind and count, and
    the XLA flags the process was launched with. Two processes with the
    same fingerprint can exchange compiled programs; anything else must
    not even try."""
    import jax

    try:
        import jaxlib
        jaxlib_ver = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover — jaxlib always ships with jax
        jaxlib_ver = "?"
    devs = jax.devices()
    parts = [
        f"jax={jax.__version__}",
        f"jaxlib={jaxlib_ver}",
        f"platform={jax.default_backend()}",
        f"device={devs[0].device_kind if devs else 'none'}",
        f"count={len(devs)}",
        f"xla_flags={os.environ.get('XLA_FLAGS', '')}",
    ]
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def key_digest(key: str) -> str:
    """Stable filename for an arbitrary program key (keys embed shapes,
    dtypes, and config digests — too long and too hostile for paths)."""
    return hashlib.sha256(key.encode()).hexdigest()[:32]


class ProgramStore:
    """One compiled-program cache directory (see module docstring)."""

    def __init__(self, root: str, *,
                 size_budget_bytes: Optional[int] = None,
                 fingerprint: Optional[str] = None):
        self.root = os.path.abspath(root)
        if size_budget_bytes is None:
            mb = os.environ.get(BUDGET_ENV)
            size_budget_bytes = (int(float(mb) * 1024 * 1024) if mb
                                 else DEFAULT_BUDGET_BYTES)
        self.size_budget_bytes = int(size_budget_bytes)
        self.fingerprint = fingerprint or runtime_fingerprint()
        self.dir = os.path.join(self.root, _LAYOUT, self.fingerprint)
        from deeplearning4j_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_hits = reg.counter(
            "dl4j_compile_cache_hits",
            "compiled programs loaded from the persistent cache "
            "(tracing AND XLA compilation skipped)")
        self._m_misses = reg.counter(
            "dl4j_compile_cache_misses",
            "programs compiled because the persistent cache had no "
            "loadable entry (then written back)")
        self._m_evict = reg.counter(
            "dl4j_compile_cache_evictions",
            "cache entries removed, by reason: lru (size budget), "
            "fingerprint (stale runtime quarantined), torn (failed "
            "CRC — skipped, never loaded), load_error (deserialize "
            "rejected the payload)")
        self._m_bytes = reg.gauge(
            "dl4j_compile_cache_bytes",
            "bytes held by the persistent compile cache (current "
            "fingerprint)")
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._sweep_stale_fingerprints()
        except OSError as e:  # unusable dir: behave as always-miss
            log.warning("compile cache %s unusable: %s", self.root, e)
        self._m_bytes.set(self._bytes())

    # ------------------------------------------------------- fingerprint
    def _sweep_stale_fingerprints(self) -> None:
        """Quarantine-and-delete entries written by a different runtime.
        They live under a different subdirectory, so they were never
        loadable from this process to begin with — the sweep just
        reclaims the bytes and makes the defense visible in metrics."""
        base = os.path.join(self.root, _LAYOUT)
        try:
            names = os.listdir(base)
        except OSError:
            return
        for name in names:
            if name == self.fingerprint:
                continue
            stale = os.path.join(base, name)
            removed = 0
            for dirpath, _dirs, files in os.walk(stale, topdown=False):
                for fn in files:
                    try:
                        os.unlink(os.path.join(dirpath, fn))
                        if fn.endswith(_SUFFIX):
                            removed += 1
                    except OSError:
                        pass
                try:
                    os.rmdir(dirpath)
                except OSError:
                    pass
            if removed:
                self._m_evict.labels(reason="fingerprint").inc(removed)
                log.info("compile cache: quarantined %d stale entries "
                         "(fingerprint %s != %s)", removed, name,
                         self.fingerprint)

    # ------------------------------------------------------------- paths
    def _path(self, key: str) -> str:
        return os.path.join(self.dir, key_digest(key) + _SUFFIX)

    def __contains__(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def keys(self) -> set:
        """Digests of the entries currently committed (the round-trip
        tests compare these sets across record/replay processes)."""
        try:
            return {fn[:-len(_SUFFIX)] for fn in os.listdir(self.dir)
                    if fn.endswith(_SUFFIX)}
        except OSError:
            return set()

    def _bytes(self) -> int:
        total = 0
        try:
            for fn in os.listdir(self.dir):
                if fn.endswith(_SUFFIX):
                    try:
                        total += os.path.getsize(
                            os.path.join(self.dir, fn))
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    # --------------------------------------------------------------- put
    def put(self, key: str, payload: bytes) -> bool:
        """Commit one serialized program crash-atomically. Returns False
        (and leaves any previous committed entry intact) on ANY failure
        — the caller already holds the compiled program, so a failed
        write costs the NEXT process a compile, nothing more."""
        path = self._path(key)
        tmp = path + ".tmp"
        blob = (_MAGIC
                + _HEADER.pack(binascii.crc32(payload) & 0xFFFFFFFF,
                               len(payload))
                + payload)
        try:
            chaos.hit("compile.cache_write", op="write", key=key)
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            chaos.hit("compile.cache_write", op="rename", key=key)
            os.replace(tmp, path)
        except BaseException as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            log.warning("compile cache write %s failed (%s: %s) — "
                        "degrading to plain compile next boot",
                        key_digest(key), type(e).__name__, e)
            if not isinstance(e, Exception):  # KeyboardInterrupt etc.
                raise
            return False
        self.gc()
        return True

    # --------------------------------------------------------------- get
    def get(self, key: str) -> Optional[bytes]:
        """The committed payload for `key`, or None (missing, torn, or
        faulted — all of which mean "compile it yourself"). A torn
        entry is deleted on sight so it cannot keep failing CRC on
        every boot."""
        path = self._path(key)
        try:
            chaos.hit("compile.cache_read", key=key)
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except Exception as e:
            log.warning("compile cache read %s failed (%s: %s) — "
                        "compiling instead", key_digest(key),
                        type(e).__name__, e)
            return None
        payload = self._validate(blob)
        if payload is None:
            self.invalidate(key, reason="torn")
            return None
        try:  # LRU touch: a loaded program is a recently-used program
            os.utime(path)
        except OSError:
            pass
        return payload

    def _validate(self, blob: bytes) -> Optional[bytes]:
        if len(blob) < len(_MAGIC) + _HEADER.size:
            return None
        if not blob.startswith(_MAGIC):
            return None
        crc, length = _HEADER.unpack_from(blob, len(_MAGIC))
        payload = blob[len(_MAGIC) + _HEADER.size:]
        if len(payload) != length:
            return None
        if binascii.crc32(payload) & 0xFFFFFFFF != crc:
            return None
        return payload

    def invalidate(self, key: str, *, reason: str) -> None:
        """Delete one entry and count the eviction (torn bytes, or a
        payload `deserialize_and_load` rejected)."""
        try:
            os.unlink(self._path(key))
        except OSError:
            pass
        self._m_evict.labels(reason=reason).inc()
        log.warning("compile cache entry %s evicted (%s)",
                    key_digest(key), reason)

    # ---------------------------------------------------------------- gc
    def gc(self) -> int:
        """Evict least-recently-used entries until under the byte
        budget; returns the number evicted. Runs after every put."""
        try:
            entries = []
            for fn in os.listdir(self.dir):
                if not fn.endswith(_SUFFIX):
                    continue
                p = os.path.join(self.dir, fn)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
        except OSError:
            return 0
        total = sum(size for _, size, _ in entries)
        evicted = 0
        if total > self.size_budget_bytes:
            for _mtime, size, p in sorted(entries):
                if total <= self.size_budget_bytes:
                    break
                try:
                    os.unlink(p)
                except OSError:
                    continue
                total -= size
                evicted += 1
            if evicted:
                self._m_evict.labels(reason="lru").inc(evicted)
        self._m_bytes.set(total)
        return evicted

    # ------------------------------------------------------------- stats
    def record_hit(self) -> None:
        self._m_hits.inc()

    def record_miss(self) -> None:
        self._m_misses.inc()

    def evictions(self) -> Dict[str, int]:
        return {labels.get("reason", "?"): int(child.value)
                for labels, child in self._m_evict.children()}

    def stats(self) -> dict:
        """The /stats "compile_cache" section (process-global counters
        next to this store's directory identity)."""
        return {
            "dir": self.root,
            "fingerprint": self.fingerprint,
            "entries": len(self.keys()),
            "bytes": self._bytes(),
            "size_budget_bytes": self.size_budget_bytes,
            "hits": int(self._m_hits.value),
            "misses": int(self._m_misses.value),
            "evictions": self.evictions(),
        }
