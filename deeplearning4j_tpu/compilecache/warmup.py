"""Warmup plans: record the program set a replica compiled; replay it
at boot.

A warmup plan is a small JSON document describing every program a
serving process needed — predict buckets, decode prefill/prefill-ctx
bucket pairs, the decode step (incl. kernel lane), speculative verify
widths, the draft scan — in LOGICAL terms (bucket sizes, shapes),
not serialized programs. The programs themselves live in the
`ProgramStore`; the plan is the table of contents that tells a fresh
process WHICH signatures to `AotDispatch.warm()` before opening
`/readyz`, so a warm-cache replica loads its entire program set in
seconds and then serves with `recompiled_after_warmup == 0`.

Plans are written with the same crash-atomic idiom as cache entries
and carry the runtime fingerprint: a plan recorded under a different
jax/backend is ignored (the cache it points at was quarantined
anyway). `serve_network(..., warmup_plan="auto")` resolves the plan
path inside the cache dir from the engine's cache key, so record and
replay need no coordination beyond sharing the cache directory.

Format (docs/WARMUP.md has the field-by-field reference):

    {"version": 1, "fingerprint": "<runtime>",
     "engines": [{"cache_key": ..., "buckets": [...],
                  "feature_shape": [...], "dtype": "<f4"}, ...],
     "decode": {"cache_key": ..., "step": true, "verify": true,
                "copy": false,
                "prefill": [[bb, tb], ...],
                "prefill_ctx": [[bb, cb, tb], ...],
                "draft": {"rows": n, "k": k}} | null}

The engine/decode flags record what the source replica actually USED
(e.g. "copy" is true only if a prefix-cache fork really dispatched the
copy program), so a replayed process loads exactly the recorded
program set — the round-trip invariant the tests pin is that record →
replay yields identical store key sets.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

from deeplearning4j_tpu.compilecache.store import (key_digest,
                                                   runtime_fingerprint)

__all__ = ["save_plan", "load_plan", "auto_plan_path", "replay_plan",
           "PLAN_VERSION"]

log = logging.getLogger(__name__)

PLAN_VERSION = 1


def auto_plan_path(cache_root: str, cache_key: str,
                   role: Optional[str] = None) -> str:
    """Where `warmup_plan="auto"` records/finds the plan for an engine
    identity: co-located in the cache dir, keyed like the programs.

    `role` scopes the plan to a disaggregated replica role
    (docs/FLEET.md "Disaggregated roles"): a prefill replica's plan
    records only the prefill lanes and a decode replica's only the
    decode ladder, so neither warms the other's programs. The
    unified/None role keeps the legacy digest — existing plans stay
    valid across the upgrade."""
    key = cache_key
    if role and role != "unified":
        key = f"{cache_key}|role={role}"
    return os.path.join(os.path.abspath(cache_root), "plans",
                        key_digest(key) + ".json")


def save_plan(path: str, plan: Dict[str, Any]) -> bool:
    """Atomic write (tmp -> fsync -> rename); stamps version and
    fingerprint. Returns False instead of raising — a failed plan
    write costs the next boot a cold compile, nothing more."""
    doc = dict(plan)
    doc.setdefault("version", PLAN_VERSION)
    doc.setdefault("fingerprint", runtime_fingerprint())
    tmp = path + ".tmp"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return True
    except OSError as e:
        log.warning("warmup plan write %s failed: %s", path, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_plan(path: str) -> Optional[Dict[str, Any]]:
    """The plan at `path`, or None for missing/torn/wrong-version/
    wrong-fingerprint — every one of which means "warm up the usual
    way" (the plan is an accelerant, never a requirement)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        log.warning("warmup plan %s unreadable (%s) — ignoring", path, e)
        return None
    if not isinstance(doc, dict) or doc.get("version") != PLAN_VERSION:
        log.warning("warmup plan %s has unsupported version %r — "
                    "ignoring", path, doc.get("version")
                    if isinstance(doc, dict) else None)
        return None
    fp = runtime_fingerprint()
    if doc.get("fingerprint") != fp:
        log.info("warmup plan %s recorded under fingerprint %s, "
                 "runtime is %s — ignoring", path,
                 doc.get("fingerprint"), fp)
        return None
    return doc


def replay_plan(plan: Dict[str, Any], *, engines=(), loops=()) -> dict:
    """Drive each engine/decode-loop's own warm hooks from the plan's
    fragments (duck-typed: `warmup_from_plan` / `warm_programs`).
    Per-object failures degrade to that object's normal cold warmup;
    the report says what happened."""
    report = {"engines": 0, "loops": 0, "errors": 0}
    frags = {f.get("cache_key"): f
             for f in plan.get("engines") or [] if f}
    for eng in engines:
        frag = frags.get(getattr(eng, "cache_key", None))
        if frag is None:
            continue
        try:
            eng.warmup_from_plan(frag)
            report["engines"] += 1
        except Exception as e:
            report["errors"] += 1
            log.warning("plan replay failed on engine (%s: %s) — "
                        "falling back to standard warmup",
                        type(e).__name__, e)
    dfrag = plan.get("decode")
    if dfrag:
        for loop in loops:
            try:
                loop.warm_programs(dfrag)
                report["loops"] += 1
            except Exception as e:
                report["errors"] += 1
                log.warning("plan replay failed on decode loop "
                            "(%s: %s) — programs will compile on "
                            "first use", type(e).__name__, e)
    return report
