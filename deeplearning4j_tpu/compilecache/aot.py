"""AOT compile-or-load: wrap a `jax.jit` callable with a persistent
program cache.

`AotDispatch` is the dispatch layer of the warm-start subsystem
(docs/WARMUP.md). It fronts ONE jitted callable and, per distinct
argument signature (shapes + dtypes + static values + pytree
structure), either

- **loads** a serialized executable from the `ProgramStore`
  (`jax.experimental.serialize_executable.deserialize_and_load` —
  skips tracing AND XLA compilation, the whole cold-boot tax), or
- **compiles** via the AOT workflow `jit_fn.lower(*args).compile()`
  and writes the serialized executable back for the next process.

Calling conventions (probed against the in-tree jax):

- `lower()` takes the FULL argument list, static args included, and
  accepts `jax.ShapeDtypeStruct` placeholders for array arguments —
  which is how `warm()` precompiles a program set without executing
  anything (execution during warmup would donate buffers and mutate
  state like the decode loop's page pool).
- A `Compiled` (fresh or deserialized) is invoked WITHOUT the static
  args — they are baked into the program — so `__call__` strips the
  static positions before dispatching to a cached executable.
- A deserialized executable accepts plain host numpy arrays and
  commits them to the devices it was compiled for.

Every failure in the AOT path (store fault, deserialize rejection,
un-serializable executable, exotic argument) falls back PERMANENTLY
(per signature) to the wrapped jit — behavior identical to not having
a cache, never an error surfaced to the caller.

`_cache_size()` mirrors the private accounting attribute on jitted
callables so `utils.jitcache.jit_cache_size` — and every recompile
guard and program-count pin built on it — sees AOT-loaded programs
and traced programs as one number, with zero changes to callers.
"""

from __future__ import annotations

import hashlib
import logging
import pickle
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax

from deeplearning4j_tpu.compilecache.store import ProgramStore

__all__ = ["AotCompiler", "AotDispatch", "config_digest"]

log = logging.getLogger(__name__)


def config_digest(obj: Any) -> str:
    """Short stable digest of a config-ish object (dataclass, dict, or
    anything with a deterministic repr) for embedding in program keys.
    Two configs that produce different jitted programs at identical
    input shapes — different layer sizes, kernels, horizons — must
    land on different keys."""
    import dataclasses

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        obj = dataclasses.asdict(obj)
    if isinstance(obj, dict):
        text = repr(sorted(obj.items()))
    else:
        text = repr(obj)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def _sig_entries(args: Sequence[Any]) -> Tuple:
    """Hashable per-argument signature: (shape, dtype) for array-likes
    (jax arrays, numpy arrays, ShapeDtypeStructs), ("py", repr) for
    static python values. Pytree containers are flattened with their
    structure recorded, so two arg lists that flatten to the same
    leaves but different trees cannot share a program."""
    entries = []
    for a in args:
        leaves, treedef = jax.tree_util.tree_flatten(a)
        leaf_sigs = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is not None and dtype is not None:
                leaf_sigs.append((tuple(shape), str(dtype)))
            else:
                leaf_sigs.append(("py", repr(leaf)))
        entries.append((str(treedef), tuple(leaf_sigs)))
    return tuple(entries)


class AotCompiler:
    """Serialize/deserialize bridge between Compiled executables and a
    `ProgramStore`. Shared by every `AotDispatch` in the process."""

    def __init__(self, store: ProgramStore):
        self.store = store

    def load(self, key: str):
        """The stored executable for `key`, loaded, or None. A payload
        the runtime refuses to deserialize is quarantined so it cannot
        fail again next boot."""
        payload = self.store.get(key)
        if payload is None:
            return None
        try:
            from jax.experimental import serialize_executable

            triple = pickle.loads(payload)
            return serialize_executable.deserialize_and_load(*triple)
        except Exception as e:
            log.warning("compile cache: deserialize failed for %s "
                        "(%s: %s) — recompiling", key,
                        type(e).__name__, e)
            self.store.invalidate(key, reason="load_error")
            return None

    def save(self, key: str, compiled):
        """Serialize, VALIDATE, and commit one executable. Returns True
        (persisted), "invalid" (the payload fails to load back — see
        below), or False (unserializable / store write fault). Never
        raises.

        The validation load-back exists because jax's own persistent
        compilation cache (JAX_COMPILATION_CACHE_DIR) can hand
        `compile()` an executable whose serialized payload is missing
        its object code — it serializes fine and then fails
        `deserialize_and_load` with "Symbols not found". Persisting
        that would poison every warm boot; "invalid" tells the
        dispatcher to recompile once with that cache bypassed."""
        try:
            from jax.experimental import serialize_executable

            triple = serialize_executable.serialize(compiled)
            payload = pickle.dumps(triple)
        except Exception as e:
            log.warning("compile cache: serialize failed for %s "
                        "(%s: %s) — entry not persisted", key,
                        type(e).__name__, e)
            return False
        try:
            serialize_executable.deserialize_and_load(
                *pickle.loads(payload))
        except Exception as e:
            log.warning("compile cache: payload for %s fails to load "
                        "back (%s: %s) — executable likely served from "
                        "jax's own compilation cache; will recompile "
                        "uncached", key, type(e).__name__, e)
            return "invalid"
        return self.store.put(key, payload)


class AotDispatch:
    """Callable wrapper: persistent-cache AOT dispatch over one
    `jax.jit` function (see module docstring). Drop-in: same call
    signature, same outputs, donation/device semantics baked into the
    loaded executables."""

    def __init__(self, jit_fn, *, key: str, compiler: AotCompiler,
                 static_argnums: Sequence[int] = ()):
        self._jit = jit_fn
        self.key = key
        self._compiler = compiler
        self._static = tuple(static_argnums)
        self._programs: Dict[Tuple, Any] = {}   # sig -> Compiled
        self._fallback: set = set()             # sigs pinned to plain jit
        self._lock = threading.Lock()

    # ------------------------------------------------------------ keys
    def _store_key(self, sig: Tuple) -> str:
        digest = hashlib.sha256(repr(sig).encode()).hexdigest()[:24]
        return f"{self.key}:{digest}"

    def keys_for(self, *args) -> str:
        """The store key this argument list dispatches to (round-trip
        tests compare these across processes)."""
        return self._store_key(_sig_entries(args))

    # -------------------------------------------------------- dispatch
    def _obtain(self, sig: Tuple, args: Sequence[Any]):
        """Load-or-compile the program for `sig`; None pins the sig to
        the plain-jit fallback. Caller holds no lock; the store is
        process-safe (atomic rename) and double-compile is benign."""
        key = self._store_key(sig)
        compiled = self._compiler.load(key)
        if compiled is not None:
            self._compiler.store.record_hit()
            return compiled
        try:
            compiled = self._jit.lower(*args).compile()
        except Exception as e:
            log.warning("AOT lower/compile failed for %s (%s: %s) — "
                        "falling back to jit dispatch", key,
                        type(e).__name__, e)
            return None
        self._compiler.store.record_miss()
        if self._compiler.save(key, compiled) == "invalid":
            fresh = self._compile_uncached(args)
            if fresh is not None \
                    and self._compiler.save(key, fresh) is True:
                compiled = fresh
        return compiled

    def _compile_uncached(self, args: Sequence[Any]):
        """Recompile with jax's persistent compilation cache bypassed —
        the remedy for cache-served executables whose serialized
        payload is unloadable (see AotCompiler.save).

        Disabling the config flag alone is NOT enough, twice over:

        - jax memoizes the cache-is-used decision process-wide on the
          first compile (`compilation_cache.is_cache_used`), so the
          flag is never re-read. `reset_cache()` drops that memo;
          resetting inside the disabled context makes the re-check see
          "disabled", and resetting again afterwards re-arms the cache
          for every later compile in the process.
        - jax ALSO memoizes compiled executables in-memory
          (`pxla._cached_compilation`, a weakref LRU keyed by the
          lowered module) — without clearing it, `lower().compile()`
          hands back the very same defective executable and XLA is
          never invoked. Clearing costs recompiles for other live jits
          only if they re-trace, and this path runs at most once per
          poisoned program."""
        try:
            from jax._src import compilation_cache as jax_cc
            from jax._src.config import enable_compilation_cache
            from jax._src.interpreters import pxla
        except Exception:
            return None
        try:
            with enable_compilation_cache(False):
                jax_cc.reset_cache()
                pxla._cached_compilation.cache_clear()
                try:
                    return self._jit.lower(*args).compile()
                finally:
                    jax_cc.reset_cache()
        except Exception as e:
            log.warning("AOT uncached recompile failed for %s "
                        "(%s: %s) — keeping the in-process program; "
                        "entry not persisted", self.key,
                        type(e).__name__, e)
            return None

    def __call__(self, *args):
        sig = _sig_entries(args)
        with self._lock:
            compiled = self._programs.get(sig)
            fallback = sig in self._fallback
        if compiled is None and not fallback:
            compiled = self._obtain(sig, args)
            with self._lock:
                if compiled is None:
                    self._fallback.add(sig)
                else:
                    self._programs.setdefault(sig, compiled)
        if compiled is None:
            return self._jit(*args)
        call_args = [a for i, a in enumerate(args)
                     if i not in self._static]
        try:
            return compiled(*call_args)
        except Exception as e:
            # a loaded program that won't execute (layout drift, device
            # mismatch) must not poison serving: pin to plain jit
            log.warning("AOT executable for %s failed at call time "
                        "(%s: %s) — pinned to jit fallback", self.key,
                        type(e).__name__, e)
            with self._lock:
                self._programs.pop(sig, None)
                self._fallback.add(sig)
            return self._jit(*args)

    # ---------------------------------------------------------- warmup
    def warm(self, *args) -> bool:
        """Load-or-compile the program for this argument signature
        WITHOUT executing it. Arguments may be (and for donating
        programs must be) `jax.ShapeDtypeStruct` placeholders; static
        args are passed as real values. Returns True if the program is
        resident afterwards."""
        sig = _sig_entries(args)
        with self._lock:
            if sig in self._programs:
                return True
            if sig in self._fallback:
                return False
        compiled = self._obtain(sig, args)
        with self._lock:
            if compiled is None:
                self._fallback.add(sig)
                return False
            self._programs.setdefault(sig, compiled)
        return True

    # ------------------------------------------------------ accounting
    def _cache_size(self) -> int:
        """Resident program count: AOT-held executables plus anything
        the fallback jit traced. `utils.jitcache.jit_cache_size` calls
        this, which keeps every recompile pin in the tree working
        unchanged on wrapped callables."""
        inner = 0
        try:
            inner = int(self._jit._cache_size())
        except Exception:
            pass
        with self._lock:
            return len(self._programs) + inner

    def aot_programs(self) -> int:
        with self._lock:
            return len(self._programs)

    def store_keys(self) -> set:
        """Store keys of the programs this dispatcher has resident."""
        with self._lock:
            sigs = list(self._programs)
        return {self._store_key(s) for s in sigs}

    # jit-attribute passthrough (e.g. .lower for diagnostics)
    def __getattr__(self, name):
        return getattr(self._jit, name)
