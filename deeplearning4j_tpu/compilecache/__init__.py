"""AOT warm-start: persistent program cache + warmup plans.

Every machine that spawns a serving or training process used to pay
full jit compilation before doing useful work — the autoscaler, router
capacity repair, canary promotion, SLO scale-up, and elastic respawn
all brought up replicas that compiled their whole program set (bucket
ladder, decode step, prefill-ctx pairs, verify widths, draft scan)
before `/readyz` flipped. This package makes the program set a
persisted artifact instead:

- `store`   — fingerprinted, crash-atomic, LRU-bounded on-disk store of
              serialized XLA executables;
- `aot`     — `AotDispatch`, the jit wrapper that loads-or-compiles
              per argument signature through the store;
- `warmup`  — JSON warmup plans: record the program set one replica
              compiled, replay it on the next boot via
              `lower().compile()` / deserialize, no execution needed.

Process activation model: ONE optional process-global compiler. When
inactive (the default — no env var, no `activate()` call) every hook
in the tree (`maybe_wrap`) is an identity function and nothing about
compilation changes. Activation happens explicitly (`cli serve
--compile-cache DIR`, `serve_network(compile_cache=...)`) or lazily
from the environment: spawners stamp `DL4J_TPU_COMPILE_CACHE` into
child environments (`export_env`), so fleet members, pipeline
replicas, and elastic workers inherit the cache with no per-call-site
plumbing. Runbook and tuning: docs/WARMUP.md.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from deeplearning4j_tpu.compilecache.aot import (  # noqa: F401
    AotCompiler,
    AotDispatch,
    config_digest,
)
from deeplearning4j_tpu.compilecache.store import (  # noqa: F401
    ProgramStore,
    key_digest,
    runtime_fingerprint,
)

__all__ = [
    "CACHE_ENV",
    "ProgramStore", "AotCompiler", "AotDispatch",
    "config_digest", "key_digest", "runtime_fingerprint",
    "activate", "deactivate", "active_compiler", "active_dir",
    "maybe_wrap", "export_env", "default_dir_for_checkpoints", "stats",
]

log = logging.getLogger(__name__)

#: child processes find their cache dir here (spawners set it; see
#: `export_env`)
CACHE_ENV = "DL4J_TPU_COMPILE_CACHE"

_lock = threading.Lock()
_compiler: Optional[AotCompiler] = None
_env_checked = False


def activate(root: str, *, size_budget_bytes: Optional[int] = None,
             fingerprint: Optional[str] = None) -> AotCompiler:
    """Open (or switch to) the persistent cache at `root` for this
    process and export it to future children via the environment.
    Idempotent for the same root."""
    global _compiler, _env_checked
    root = os.path.abspath(root)
    with _lock:
        if _compiler is not None and _compiler.store.root == root:
            return _compiler
        _compiler = AotCompiler(ProgramStore(
            root, size_budget_bytes=size_budget_bytes,
            fingerprint=fingerprint))
        _env_checked = True
        os.environ[CACHE_ENV] = root
        log.info("compile cache active at %s (fingerprint %s)",
                 root, _compiler.store.fingerprint)
        return _compiler


def deactivate() -> None:
    """Drop the process-global compiler and the env export. Callables
    already wrapped keep their loaded programs; new `maybe_wrap` calls
    become identity again. (Primarily for tests.)"""
    global _compiler, _env_checked
    with _lock:
        _compiler = None
        _env_checked = True
        os.environ.pop(CACHE_ENV, None)


def active_compiler() -> Optional[AotCompiler]:
    """The process compiler, auto-activating once from
    `DL4J_TPU_COMPILE_CACHE` — how spawned children pick up the cache
    their parent exported without any code path knowing about it."""
    global _compiler, _env_checked
    with _lock:
        if _compiler is None and not _env_checked:
            _env_checked = True
            root = os.environ.get(CACHE_ENV)
            if root:
                try:
                    _compiler = AotCompiler(ProgramStore(root))
                    log.info("compile cache activated from env: %s",
                             root)
                except Exception as e:
                    log.warning("compile cache env activation failed "
                                "(%s: %s) — running uncached",
                                type(e).__name__, e)
        return _compiler


def active_dir() -> Optional[str]:
    comp = active_compiler()
    return comp.store.root if comp is not None else None


def maybe_wrap(jit_fn, key: Optional[str], *,
               static_argnums=()):
    """The one hook call sites use: wrap `jit_fn` in an `AotDispatch`
    when a cache is active and a key is given, else return it
    untouched. Call sites therefore carry zero cache logic and zero
    behavior change when the subsystem is off."""
    if key is None:
        return jit_fn
    comp = active_compiler()
    if comp is None:
        return jit_fn
    return AotDispatch(jit_fn, key=key, compiler=comp,
                       static_argnums=static_argnums)


def export_env(env: dict) -> dict:
    """Stamp the active cache dir into a child-process environment
    (spawners call this; no-op when inactive or already set by the
    caller). Returns `env` for chaining."""
    comp = active_compiler()
    if comp is not None and CACHE_ENV not in env:
        env[CACHE_ENV] = comp.store.root
    return env


def default_dir_for_checkpoints(checkpoint_dir: str) -> str:
    """`--compile-cache auto`: co-locate the program cache with the
    checkpoint dir, so whatever ships/mounts checkpoints ships warm
    programs too."""
    return os.path.join(os.path.abspath(checkpoint_dir),
                        "compile_cache")


def stats() -> Optional[dict]:
    """The active store's stats dict (the /stats "compile_cache"
    section), or None when inactive."""
    comp = active_compiler()
    return comp.store.stats() if comp is not None else None
