"""Visualization: t-SNE, network plotters, render server.

Parity: reference core/plot/ — `Tsne` (Tsne.java: gradient t-SNE with
perplexity-searched affinities), `BarnesHutTsne` (BarnesHutTsne.java:
quadtree-approximated O(n log n) gradient), `NeuralNetPlotter`
(NeuralNetPlotter.java shells out to python/matplotlib scripts — here
matplotlib is called directly, no Runtime.exec), `FilterRenderer` (weight
grids) and the dropwizard coords server (nlp/plot/dropwizard/
RenderApplication.java — here a stdlib http.server).
"""

from deeplearning4j_tpu.plot.tsne import BarnesHutTsne, Tsne  # noqa: F401
from deeplearning4j_tpu.plot.plotter import NeuralNetPlotter  # noqa: F401
from deeplearning4j_tpu.plot.render_server import serve_coords  # noqa: F401
