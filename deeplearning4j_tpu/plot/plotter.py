"""Network visualization: weight histograms, activation renders, filter grids.

Parity: reference core/plot/NeuralNetPlotter.java (plotWeightHistograms
:164, plotActivations :196, renderGraph via Runtime.exec("python plot.py")
:245 + bundled scripts/plot.py|render.py) and FilterRenderer (557 LoC
weight-grid images). Matplotlib is invoked in-process (Agg backend) instead
of shelling out, and a hook is provided as an IterationListener so renders
happen during training like NeuralNetPlotterIterationListener.
"""

from __future__ import annotations

import math
import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener


def _plt():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


class NeuralNetPlotter:
    def __init__(self, out_dir: str = "plots"):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)

    def plot_weight_histograms(self, network, path: Optional[str] = None
                               ) -> str:
        """One histogram per named parameter (plotWeightHistograms :164)."""
        plt = _plt()
        tables = network.param_table
        names = [(li, name) for li, t in tables.items() for name in t]
        cols = max(1, min(4, len(names)))
        rows = math.ceil(len(names) / cols)
        fig, axes = plt.subplots(rows, cols, figsize=(4 * cols, 3 * rows),
                                 squeeze=False)
        for ax in axes.flat:
            ax.axis("off")
        for k, (li, name) in enumerate(names):
            ax = axes[k // cols][k % cols]
            ax.axis("on")
            ax.hist(np.asarray(tables[li][name]).ravel(), bins=50)
            ax.set_title(f"layer {li} / {name}", fontsize=8)
        path = path or os.path.join(self.out_dir, "weight_histograms.png")
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        return path

    def plot_activations(self, network, x, path: Optional[str] = None) -> str:
        """Heatmap of each layer's activations on a batch
        (plotActivations :196)."""
        plt = _plt()
        acts = network.feed_forward(np.asarray(x))
        fig, axes = plt.subplots(1, len(acts), figsize=(4 * len(acts), 4),
                                 squeeze=False)
        for i, act in enumerate(acts):
            a = np.asarray(act)
            if a.ndim > 2:
                a = a.reshape(a.shape[0], -1)
            axes[0][i].imshow(a, aspect="auto", cmap="viridis")
            axes[0][i].set_title("input" if i == 0 else f"layer {i - 1}",
                                 fontsize=8)
        path = path or os.path.join(self.out_dir, "activations.png")
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        return path

    def render_filters(self, weights, image_shape, path: Optional[str] = None,
                       cols: int = 10) -> str:
        """Tile first-layer weights as image patches
        (reference FilterRenderer)."""
        plt = _plt()
        w = np.asarray(weights)
        if w.ndim == 4:  # HWIO conv filters -> one (fh*fw*cin,) row per map
            filters = np.transpose(w, (3, 0, 1, 2)).reshape(w.shape[3], -1)
            image_shape = image_shape or (w.shape[0], w.shape[1] * w.shape[2])
        else:  # dense W (n_in, n_out): each column is a filter over the input
            filters = w.T
        n = filters.shape[0]
        rows = math.ceil(n / cols)
        fig, axes = plt.subplots(rows, cols, figsize=(cols, rows),
                                 squeeze=False)
        for ax in axes.flat:
            ax.axis("off")
        for k in range(n):
            img = filters[k].reshape(image_shape)
            axes[k // cols][k % cols].imshow(img, cmap="gray")
        path = path or os.path.join(self.out_dir, "filters.png")
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        return path


class PlotterIterationListener(IterationListener):
    """Render every N iterations during training
    (reference NeuralNetPlotterIterationListener)."""

    def __init__(self, plotter: Optional[NeuralNetPlotter] = None,
                 every: int = 10):
        self.plotter = plotter or NeuralNetPlotter()
        self.every = every

    def iteration_done(self, model, iteration: int, score: float) -> None:
        if iteration % self.every == 0 and model is not None:
            try:
                self.plotter.plot_weight_histograms(model)
            except Exception:  # rendering must never kill training
                pass
