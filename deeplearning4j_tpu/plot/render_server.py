"""Lightweight coords render server.

Parity: reference nlp/plot/dropwizard/ — `RenderApplication` (Dropwizard
boot :37) + `ApiResource` GET /api/coords serving coords.csv
(ApiResource.java:44-60). Here: a stdlib ThreadingHTTPServer serving the
2D embedding + word labels as JSON at /api/coords and a minimal scatter
page at /.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.utils.httpd import ServerHandle, start_http_server

_PAGE = b"""<!doctype html><html><body>
<canvas id=c width=900 height=900></canvas><script>
fetch('/api/coords').then(r=>r.json()).then(d=>{
 const ctx=document.getElementById('c').getContext('2d');
 const xs=d.coords.map(p=>p[0]), ys=d.coords.map(p=>p[1]);
 const minx=Math.min(...xs),maxx=Math.max(...xs);
 const miny=Math.min(...ys),maxy=Math.max(...ys);
 d.coords.forEach((p,i)=>{
  const x=40+(p[0]-minx)/(maxx-minx+1e-9)*820;
  const y=40+(p[1]-miny)/(maxy-miny+1e-9)*820;
  ctx.fillText(d.labels[i]||'.',x,y);});});
</script></body></html>"""


def serve_coords(coords: np.ndarray, labels: Optional[Sequence[str]] = None,
                 port: int = 0) -> ServerHandle:
    """Start the render server (daemon thread) on an auto-assigned port
    by default; returns a ServerHandle — call handle.close() to stop and
    release the socket (it also unpacks as the historical
    (server, port) pair)."""
    coords = np.asarray(coords, np.float64)
    payload = json.dumps({
        "coords": coords[:, :2].tolist(),
        "labels": list(labels) if labels is not None else
        [""] * coords.shape[0],
    }).encode()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/api/coords"):
                body, ctype = payload, "application/json"
            else:
                body, ctype = _PAGE, "text/html"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet
            pass

    return start_http_server(Handler, port=port)
