"""t-SNE: exact (device-jitted) and Barnes-Hut (quadtree) variants.

Parity: reference core/plot/Tsne.java (calculate :342 — perplexity binary
search for conditional affinities, early exaggeration, momentum gradient
iterations; plot :441 writes coords) and BarnesHutTsne.java:58 (theta-
approximated repulsive forces via QuadTree, implements Model).

TPU-native design: the exact variant keeps the WHOLE iteration loop on
device — pairwise affinities, the student-t Q matrix, and the gradient are
(n, n) matmul/reduction work that XLA fuses; for n up to ~10k exact t-SNE
on the MXU beats a host-side Barnes-Hut walk. The Barnes-Hut variant is
kept for capability parity (and very large n on the host).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.quadtree import QuadTree


def _hbeta(d_row: np.ndarray, beta: float):
    p = np.exp(-d_row * beta)
    sum_p = max(p.sum(), 1e-12)
    h = np.log(sum_p) + beta * float((d_row * p).sum()) / sum_p
    return h, p / sum_p


def binary_search_affinities(x: np.ndarray, perplexity: float = 30.0,
                             tol: float = 1e-5) -> np.ndarray:
    """Conditional P with per-point beta search (reference Tsne d2p)."""
    n = x.shape[0]
    x2 = (x * x).sum(1)
    d = x2[:, None] + x2[None, :] - 2 * x @ x.T
    np.fill_diagonal(d, 0.0)
    target = np.log(perplexity)
    p = np.zeros((n, n))
    for i in range(n):
        idx = np.concatenate([np.arange(i), np.arange(i + 1, n)])
        d_row = d[i, idx]
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        for _ in range(50):
            h, this_p = _hbeta(d_row, beta)
            diff = h - target
            if abs(diff) < tol:
                break
            if diff > 0:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        p[i, idx] = this_p
    p = (p + p.T) / (2 * n)
    return np.maximum(p, 1e-12)


class Tsne:
    """Exact t-SNE, device-jitted iterations (reference Tsne.java)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 momentum: float = 0.5, final_momentum: float = 0.8,
                 switch_momentum_iteration: int = 250,
                 early_exaggeration: float = 12.0,
                 stop_lying_iteration: int = 100, seed: int = 42):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.early_exaggeration = early_exaggeration
        self.stop_lying_iteration = stop_lying_iteration
        self.seed = seed
        self.embedding_: Optional[np.ndarray] = None

    def calculate(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        p = jnp.asarray(binary_search_affinities(
            x.astype(np.float64), self.perplexity), jnp.float32)
        key = jax.random.PRNGKey(self.seed)
        y = 1e-4 * jax.random.normal(key, (n, self.n_components), jnp.float32)

        @jax.jit
        def grad_step(y, velocity, p_eff, momentum):
            y2 = jnp.sum(y * y, axis=1)
            num = 1.0 / (1.0 + y2[:, None] + y2[None, :] - 2.0 * (y @ y.T))
            num = num.at[jnp.diag_indices(n)].set(0.0)
            q = jnp.maximum(num / jnp.sum(num), 1e-12)
            pq = (p_eff - q) * num  # (n, n)
            grad = 4.0 * (jnp.diag(pq.sum(axis=1)) - pq) @ y
            velocity = momentum * velocity - self.learning_rate * grad
            y = y + velocity
            return y - jnp.mean(y, axis=0), velocity

        velocity = jnp.zeros_like(y)
        for it in range(self.n_iter):
            p_eff = p * self.early_exaggeration \
                if it < self.stop_lying_iteration else p
            momentum = self.momentum if it < self.switch_momentum_iteration \
                else self.final_momentum
            y, velocity = grad_step(y, velocity, p_eff,
                                    jnp.float32(momentum))
        self.embedding_ = np.asarray(y)
        return self.embedding_

    def fit_transform(self, x) -> np.ndarray:
        return self.calculate(x)  # dispatches to the subclass's calculate

    def plot(self, x, labels=None, path: str = "tsne.png") -> str:
        """Render the embedding to an image (reference plot :441 shells to
        matplotlib; here it's a direct call)."""
        y = self.calculate(x) if self.embedding_ is None else self.embedding_
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, ax = plt.subplots(figsize=(8, 8))
        if labels is not None:
            labels = np.asarray(labels)
            for lbl in np.unique(labels):
                m = labels == lbl
                ax.scatter(y[m, 0], y[m, 1], s=8, label=str(lbl))
            ax.legend(markerscale=2)
        else:
            ax.scatter(y[:, 0], y[:, 1], s=8)
        fig.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(fig)
        return path


class BarnesHutTsne(Tsne):
    """theta-approximate t-SNE over a QuadTree
    (reference BarnesHutTsne.java:58)."""

    def __init__(self, theta: float = 0.5, **kw):
        kw.setdefault("n_iter", 300)
        super().__init__(**kw)
        self.theta = theta

    def calculate(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        p = binary_search_affinities(x, self.perplexity)
        rng = np.random.RandomState(self.seed)
        y = 1e-4 * rng.randn(n, 2)
        velocity = np.zeros_like(y)
        for it in range(self.n_iter):
            exag = self.early_exaggeration \
                if it < self.stop_lying_iteration else 1.0
            momentum = self.momentum if it < self.switch_momentum_iteration \
                else self.final_momentum
            # attractive forces (exact over nonzero P; P is dense here)
            y2 = (y * y).sum(1)
            num = 1.0 / (1.0 + y2[:, None] + y2[None, :] - 2 * y @ y.T)
            np.fill_diagonal(num, 0.0)
            pn = (exag * p) * num
            attr = pn.sum(1)[:, None] * y - pn @ y
            # repulsive forces via the quadtree
            tree = QuadTree(points=y)
            rep = np.zeros_like(y)
            z_total = 0.0
            for i in range(n):
                neg_f = np.zeros(2)
                z_total += tree.compute_non_edge_forces(
                    y[i], self.theta, neg_f)
                rep[i] = neg_f
            grad = 4.0 * (attr - rep / max(z_total, 1e-12))
            velocity = momentum * velocity - self.learning_rate * grad
            y = y + velocity
            y -= y.mean(0)
        self.embedding_ = y.astype(np.float32)
        return self.embedding_
