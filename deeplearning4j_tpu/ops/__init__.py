"""Tensor op surface.

The reference delegates numerics to the external ND4J library (INDArray ops,
`Nd4j.getExecutioner()` — see reference core/nn/layers/BaseLayer.java:206).
Here the equivalent surface is jax.numpy/lax lowered by XLA onto the MXU;
string-named activations / losses / weight-init schemes keep API parity with
the reference's `conf.activationFunction` / `conf.lossFunction` strings.
"""

from deeplearning4j_tpu.ops.activations import apply_activation, ACTIVATIONS  # noqa: F401
from deeplearning4j_tpu.ops.losses import loss_fn, LOSS_FUNCTIONS  # noqa: F401
from deeplearning4j_tpu.ops.initializers import init_weights, WeightInit  # noqa: F401
