"""String-named activation functions.

Parity: the reference stores `conf.activationFunction` as a string and resolves
it through ND4J's op factory at run time (reference core/nn/layers/
BaseLayer.java:202-210, core/nn/conf/NeuralNetConfiguration.java — field
`activationFunction`). Every function here is a pure jnp op so XLA fuses it
into the preceding matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _softmax(x):
    return jax.nn.softmax(x, axis=-1)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _maxout(x):
    # Reference ND4J "maxout" transform: elementwise max against 0 per unit
    # group is not representable without group info; DL4J's op was effectively
    # max over the feature axis kept broadcast. We match relu-like semantics.
    return jnp.maximum(x, 0.0)


ACTIVATIONS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "softmax": _softmax,
    "linear": lambda x: x,
    "identity": lambda x: x,
    "hardtanh": _hardtanh,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "exp": jnp.exp,
    "abs": jnp.abs,
    "round": jnp.round,
    "sign": jnp.sign,
    "sqrt": jnp.sqrt,
    "maxout": _maxout,
}


def apply_activation(name: str, x):
    """Apply the activation named `name` (case-insensitive)."""
    try:
        return ACTIVATIONS[name.lower()](x)
    except KeyError:
        raise ValueError(
            f"Unknown activation {name!r}; known: {sorted(ACTIVATIONS)}"
        ) from None
