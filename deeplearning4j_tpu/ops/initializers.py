"""Weight initialization schemes.

Parity with reference core/nn/weights/WeightInit.java enum
{VI, ZERO, SIZE, DISTRIBUTION, NORMALIZED, UNIFORM} and
`WeightInitUtil.initWeights`. RNG discipline is TPU-native: explicit
`jax.random` keys instead of the reference's shared `conf.rng`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class WeightInit:
    VI = "vi"
    ZERO = "zero"
    SIZE = "size"
    DISTRIBUTION = "distribution"
    NORMALIZED = "normalized"
    UNIFORM = "uniform"


def _fans(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) == 4:  # conv HWIO (the framework-wide TPU filter layout)
        receptive = shape[0] * shape[1]
        return shape[2] * receptive, shape[3] * receptive
    n = int(jnp.prod(jnp.array(shape)))
    return n, n


def init_weights(
    key: jax.Array,
    shape: Tuple[int, ...],
    scheme: str = WeightInit.VI,
    dist: Optional[dict] = None,
    dtype=jnp.float32,
):
    """Initialize a weight tensor.

    `dist` mirrors the reference's `conf.dist` (a RealDistribution) for the
    DISTRIBUTION scheme: {"type": "normal"|"uniform", ...params}.
    """
    scheme = scheme.lower()
    fan_in, fan_out = _fans(shape)
    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.VI:
        # Variance-scaled init (reference WeightInitUtil VI: uniform in
        # +-sqrt(6/(fanIn+fanOut)), the Glorot/Bengio scheme).
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == WeightInit.SIZE:
        r = 1.0 / jnp.sqrt(float(fan_in))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == WeightInit.UNIFORM:
        r = 1.0 / jnp.sqrt(float(fan_in))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == WeightInit.NORMALIZED:
        return (jax.random.uniform(key, shape, dtype) - 0.5) / float(fan_in)
    if scheme == WeightInit.DISTRIBUTION:
        d = dist or {"type": "normal", "mean": 0.0, "std": 0.01}
        if d.get("type", "normal") == "uniform":
            return jax.random.uniform(
                key, shape, dtype, d.get("lower", -1.0), d.get("upper", 1.0)
            )
        return d.get("mean", 0.0) + d.get("std", 0.01) * jax.random.normal(
            key, shape, dtype
        )
    raise ValueError(f"Unknown weight init scheme {scheme!r}")
