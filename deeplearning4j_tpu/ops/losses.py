"""Loss functions.

Parity with the reference's `LossFunctions.LossFunction` set and the per-loss
gradient switch in reference core/nn/layers/OutputLayer.java:131-163
(MCXENT / XENT / MSE / EXPLL / RMSE_XENT / SQUARED_LOSS /
NEGATIVELOGLIKELIHOOD / RECONSTRUCTION_CROSSENTROPY). Unlike the reference,
gradients come from jax.grad — only the scalar score is defined here.

All losses return the mean per-example score (the reference divides by the
number of examples in OutputLayer.score, OutputLayer.java:72-101) and are
written NaN-safe the way the reference scrubs NaNs via
`BooleanIndexing.applyWhere(output, isNan, EPS)` (OutputLayer.java:75,:89):
probabilities are clipped to [EPS, 1-EPS] before logs.
"""

from __future__ import annotations

import jax.numpy as jnp

EPS = 1e-7


def _clip(p):
    return jnp.clip(p, EPS, 1.0 - EPS)


def mcxent(labels, output):
    """Multi-class cross entropy: -sum(labels * log(p))."""
    return -jnp.sum(labels * jnp.log(_clip(output))) / labels.shape[0]


def xent(labels, output):
    """Binary cross entropy."""
    p = _clip(output)
    return -jnp.sum(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p)) / labels.shape[0]


def mse(labels, output):
    return jnp.sum(jnp.square(labels - output)) / (2.0 * labels.shape[0])


def expll(labels, output):
    """Exponential log-likelihood (Poisson-style): sum(p - labels*log(p))."""
    p = _clip(output)
    return jnp.sum(p - labels * jnp.log(p)) / labels.shape[0]


def rmse_xent(labels, output):
    return jnp.sum(jnp.sqrt(jnp.square(labels - output) + EPS)) / labels.shape[0]


def squared_loss(labels, output):
    return jnp.sum(jnp.square(labels - output)) / labels.shape[0]


def negativeloglikelihood(labels, output):
    """NLL over softmax output — same functional form as MCXENT here."""
    return -jnp.sum(labels * jnp.log(_clip(output))) / labels.shape[0]


def reconstruction_crossentropy(labels, output):
    """Reconstruction cross-entropy used by pretrain layers (AE/RBM score)."""
    p = _clip(output)
    return -jnp.sum(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p)) / labels.shape[0]


LOSS_FUNCTIONS = {
    "mcxent": mcxent,
    "xent": xent,
    "mse": mse,
    "expll": expll,
    "rmse_xent": rmse_xent,
    "squared_loss": squared_loss,
    "negativeloglikelihood": negativeloglikelihood,
    "reconstruction_crossentropy": reconstruction_crossentropy,
}


def loss_fn(name: str):
    try:
        return LOSS_FUNCTIONS[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown loss function {name!r}; known: {sorted(LOSS_FUNCTIONS)}"
        ) from None
