"""Loss functions.

Parity with the reference's `LossFunctions.LossFunction` set and the per-loss
gradient switch in reference core/nn/layers/OutputLayer.java:131-163
(MCXENT / XENT / MSE / EXPLL / RMSE_XENT / SQUARED_LOSS /
NEGATIVELOGLIKELIHOOD / RECONSTRUCTION_CROSSENTROPY). Unlike the reference,
gradients come from jax.grad — only the scalar score is defined here.

All losses return the mean per-example score (the reference divides by the
number of examples in OutputLayer.score, OutputLayer.java:72-101) and are
written NaN-safe the way the reference scrubs NaNs via
`BooleanIndexing.applyWhere(output, isNan, EPS)` (OutputLayer.java:75,:89):
probabilities are clipped to [EPS, 1-EPS] before logs.

Every loss takes an optional `weights` vector — per-example weights over
the leading (batch) dimension, used by the device-feed pipeline to mask
shape-bucketing padding rows out of the mean (datasets/device_feed.py):
with weights the score is sum(w_i * loss_i) / sum(w), so zero-weight
(padded) rows contribute nothing to either the value or the gradient and
the denominator is the REAL example count. `weights=None` keeps the plain
sum/B path bit-identical to the historical formulas.
"""

from __future__ import annotations

import jax.numpy as jnp

EPS = 1e-7


def _clip(p):
    return jnp.clip(p, EPS, 1.0 - EPS)


def _reduce(pointwise, weights, denom_scale: float = 1.0):
    """sum(pointwise) / (denom_scale * B), optionally example-weighted.

    The denominator floor only defends the all-masked degenerate batch
    (0/0 -> 0); fractional weights summing below 1 keep their true
    sum(w) denominator."""
    if weights is None:
        return jnp.sum(pointwise) / (denom_scale * pointwise.shape[0])
    per_example = jnp.sum(pointwise.reshape(pointwise.shape[0], -1), axis=1)
    w = weights.astype(per_example.dtype)
    denom = jnp.maximum(jnp.sum(w), jnp.finfo(per_example.dtype).tiny)
    return jnp.sum(per_example * w) / (denom_scale * denom)


def mcxent(labels, output, weights=None):
    """Multi-class cross entropy: -sum(labels * log(p))."""
    return _reduce(-labels * jnp.log(_clip(output)), weights)


def xent(labels, output, weights=None):
    """Binary cross entropy."""
    p = _clip(output)
    return _reduce(-(labels * jnp.log(p)
                     + (1.0 - labels) * jnp.log(1.0 - p)), weights)


def mse(labels, output, weights=None):
    return _reduce(jnp.square(labels - output), weights, 2.0)


def expll(labels, output, weights=None):
    """Exponential log-likelihood (Poisson-style): sum(p - labels*log(p))."""
    p = _clip(output)
    return _reduce(p - labels * jnp.log(p), weights)


def rmse_xent(labels, output, weights=None):
    return _reduce(jnp.sqrt(jnp.square(labels - output) + EPS), weights)


def squared_loss(labels, output, weights=None):
    return _reduce(jnp.square(labels - output), weights)


def negativeloglikelihood(labels, output, weights=None):
    """NLL over softmax output — same functional form as MCXENT here."""
    return _reduce(-labels * jnp.log(_clip(output)), weights)


def reconstruction_crossentropy(labels, output, weights=None):
    """Reconstruction cross-entropy used by pretrain layers (AE/RBM score)."""
    p = _clip(output)
    return _reduce(-(labels * jnp.log(p)
                     + (1.0 - labels) * jnp.log(1.0 - p)), weights)


LOSS_FUNCTIONS = {
    "mcxent": mcxent,
    "xent": xent,
    "mse": mse,
    "expll": expll,
    "rmse_xent": rmse_xent,
    "squared_loss": squared_loss,
    "negativeloglikelihood": negativeloglikelihood,
    "reconstruction_crossentropy": reconstruction_crossentropy,
}


def loss_fn(name: str):
    try:
        return LOSS_FUNCTIONS[name.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown loss function {name!r}; known: {sorted(LOSS_FUNCTIONS)}"
        ) from None
