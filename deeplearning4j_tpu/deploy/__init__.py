"""Deployment subsystem: the train→serve conveyor (docs/PIPELINE.md).

`DeploymentController` closes the loop the rest of the repo built the
two halves of: elastic training commits sharded checkpoints (PR 9/10),
the serving fleet hot-reloads them with canary + rollback (PR 7) — this
package watches the checkpoint directory, gates each newly COMMITTED
step on a held-out evaluation, and drives the fleet's canary reload,
promoting on probe success and rolling back + quarantining on failure.
Its own decisions journal through `StateFile` (controller.journal) so a
killed controller restarts into the same verdict; it runs under
`cli watchdog` like the other control planes.
"""

from deeplearning4j_tpu.deploy.controller import (  # noqa: F401
    CANARY,
    ControllerBusy,
    DeploymentController,
    EVALUATING,
    IDLE,
    PROMOTING,
    QUARANTINE_MARKER,
    ROLLING_BACK,
)

__all__ = ["DeploymentController", "ControllerBusy", "QUARANTINE_MARKER",
           "IDLE", "EVALUATING", "CANARY", "PROMOTING", "ROLLING_BACK"]
