"""Crash-safe train→serve deployment controller (docs/PIPELINE.md).

The conveyor: watch a checkpoint directory for newly COMMITTED steps
(the atomic-rename marker IS the watch primitive — bounded-interval
polling, no inotify), run an **eval gate** on each candidate (held-out
set through eval/holdout.py, absolute-score and regression-vs-champion
thresholds), then drive the fleet's canary reload (drain → reload →
`/readyz` → validation probe), **promoting** on success and **rolling
back + quarantining** on failure. A `QUARANTINED` marker in the step
dir keeps the watcher from ever re-offering a bad checkpoint; the
reason is journaled.

State machine: IDLE → EVALUATING → CANARY → PROMOTING (→ IDLE) or
→ ROLLING_BACK (→ IDLE). Every transition journals through `StateFile`
(chaos point ``controller.journal``) so a killed controller restarts
into the same decision — a promotion is either fully applied to the
fleet or fully rolled back, never torn:

- killed before CANARY: the candidate is rediscovered by the next scan
  (evaluation is idempotent);
- killed in CANARY/PROMOTING: the restart re-drives the rolling reload
  (itself idempotent — the fleet's own canary/rollback machinery makes
  the outcome all-or-nothing) and lands on the same verdict;
- killed in ROLLING_BACK: the failure verdict was already committed —
  the restart re-asserts the champion on the fleet and quarantines the
  candidate.

Failure policy — the asymmetry that keeps the conveyor honest:
*definitive* verdicts (a gate score below threshold, a canary probe
failure reported by the fleet) quarantine the candidate; *infra*
failures (the fleet unreachable, no ready replicas, a reload already in
flight, an eval that could not run) leave the candidate pending and are
retried next poll — an eval that could not run is NOT a failed eval.

Ownership: the journal carries the owner's (pid, /proc start-time)
fingerprint; a second controller pointed at the same journal refuses to
start while the fingerprint classifies as a live owner
(`ControllerBusy`) — the same pid-recycling-safe discipline as the
supervisor and fleet (utils/procs.py).

Telemetry (docs/OBSERVABILITY.md): ``dl4j_pipeline_candidates_seen``,
``dl4j_pipeline_eval_pass`` / ``_fail``, ``dl4j_pipeline_promotions`` /
``_rollbacks`` / ``_quarantines`` counters, ``dl4j_pipeline_eval_seconds``
+ ``dl4j_pipeline_promote_seconds`` histograms, and the
``dl4j_pipeline_champion_step`` gauge — all labelled ``pipeline=<name>``
— plus the shared ``dl4j_controlplane_*`` journal/restart series
(plane="pipeline"). `status_port=` serves the StatusServer surface
(/status.json with the controller state under "extra", /healthz,
/metrics).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import weakref
from typing import Dict, Optional

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.checkpoint import format as ckfmt
from deeplearning4j_tpu.checkpoint.restore import list_committed_steps
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils import procs
from deeplearning4j_tpu.utils.statefile import (StateFile,
                                                controlplane_metrics)

__all__ = ["DeploymentController", "ControllerBusy", "QUARANTINE_MARKER",
           "IDLE", "EVALUATING", "CANARY", "PROMOTING", "ROLLING_BACK"]

log = logging.getLogger(__name__)

# controller phases (the journaled state machine)
IDLE = "idle"
EVALUATING = "evaluating"
CANARY = "canary"
PROMOTING = "promoting"
ROLLING_BACK = "rolling_back"

#: marker file dropped in a rejected step dir — the watcher skips any
#: step carrying it, so a bad checkpoint is never re-offered (the
#: negative twin of the COMMITTED marker, same atomic-rename publish)
QUARANTINE_MARKER = "QUARANTINED"

_name_seq = itertools.count()


class ControllerBusy(RuntimeError):
    """Another live controller owns this journal (double-start lock)."""


class DeploymentController:
    """One conveyor: checkpoint_dir → eval gate → fleet canary promote.

    Exactly one of `fleet` (an in-process serving Fleet object) or
    `fleet_url` (a fleet router endpoint, POST /reload) carries the
    promotion. `eval_data` (held-out labelled CSV) arms the eval gate;
    without it candidates skip straight to the canary (the fleet's
    validation `probe` is then the only gate). `state_dir` arms the
    crash-safe journal + double-start lock.
    """

    def __init__(self, checkpoint_dir: str, *,
                 fleet=None, fleet_url: Optional[str] = None,
                 eval_data: Optional[str] = None,
                 eval_via_fleet: bool = False,
                 label_columns: int = 1,
                 metric: str = "f1",
                 eval_threshold: float = 0.0,
                 regression_margin: float = 0.05,
                 poll_interval: float = 2.0,
                 probe: Optional[dict] = None,
                 state_dir: Optional[str] = None,
                 name: Optional[str] = None,
                 status_port: Optional[int] = None,
                 request_timeout: float = 120.0,
                 model_id: Optional[str] = None):
        if (fleet is None) == (fleet_url is None):
            raise ValueError(
                "DeploymentController needs exactly one of fleet= "
                "(in-process) or fleet_url= (router endpoint)")
        if eval_via_fleet and fleet_url is None:
            raise ValueError(
                "eval_via_fleet scores the LIVE fleet over HTTP and "
                "needs fleet_url= (a router endpoint)")
        self.checkpoint_dir = checkpoint_dir
        self.fleet = fleet
        self.fleet_url = fleet_url.rstrip("/") if fleet_url else None
        self.eval_data = eval_data
        #: refresh the champion's regression baseline from the live
        #: fleet (batch SLO tier — bulk scoring never competes with
        #: interactive admission) instead of trusting the journaled
        #: score: a drifted holdout or a champion reloaded behind the
        #: controller's back would otherwise skew the gate
        self.eval_via_fleet = bool(eval_via_fleet)
        self.label_columns = int(label_columns)
        self.metric = metric
        self.eval_threshold = float(eval_threshold)
        self.regression_margin = float(regression_margin)
        self.poll_interval = float(poll_interval)
        self.probe = probe
        self.request_timeout = float(request_timeout)
        #: scope every reload this conveyor drives to ONE model's
        #: replicas on a multi-model fleet (docs/FLEET.md
        #: "Disaggregated roles"); None drives the whole fleet
        self.model_id = model_id
        self.name = name if name is not None else f"p{next(_name_seq)}"

        self.phase = IDLE
        #: current champion {path, step, metrics} — the rollback target
        self.champion: Optional[dict] = None
        #: in-flight candidate {path, step, metrics} while not IDLE
        self.candidate: Optional[dict] = None
        #: {step(str): reason} — quarantined steps this conveyor decided
        self.quarantined: Dict[str, str] = {}
        self.incarnation = 0
        self._seen: set = set()
        self._stop = threading.Event()
        self.started_at = time.time()

        # ----------------------------------------------------- telemetry
        reg = telemetry.get_registry()
        lab = {"pipeline": self.name}
        self._m_seen = reg.counter(
            "dl4j_pipeline_candidates_seen",
            "newly COMMITTED checkpoint steps the watcher offered the "
            "gate").labels(**lab)
        self._m_eval_pass = reg.counter(
            "dl4j_pipeline_eval_pass",
            "candidates that passed the eval gate").labels(**lab)
        self._m_eval_fail = reg.counter(
            "dl4j_pipeline_eval_fail",
            "candidates the eval gate rejected (absolute threshold or "
            "regression vs champion)").labels(**lab)
        self._m_promotions = reg.counter(
            "dl4j_pipeline_promotions",
            "candidates promoted to fleet champion").labels(**lab)
        self._m_rollbacks = reg.counter(
            "dl4j_pipeline_rollbacks",
            "failed canaries rolled back to the champion").labels(**lab)
        self._m_quarantines = reg.counter(
            "dl4j_pipeline_quarantines",
            "checkpoints quarantined (QUARANTINED marker "
            "written)").labels(**lab)
        self._m_eval_s = reg.histogram(
            "dl4j_pipeline_eval_seconds",
            "eval-gate wall time per candidate").labels(**lab)
        self._m_promote_s = reg.histogram(
            "dl4j_pipeline_promote_seconds",
            "canary promote wall time (drive + fleet convergence)"
            ).labels(**lab)
        ref = weakref.ref(self)
        reg.gauge(
            "dl4j_pipeline_champion_step",
            "committed step of the current champion (-1 = none "
            "yet)").labels(**lab).set_function(
            lambda: (lambda o: (o.champion or {}).get("step")
                     if o and o.champion else -1)(ref()))
        self._m_restarts, self._m_adoptions = controlplane_metrics(
            "pipeline", self.name,
            lambda: (lambda o: o.incarnation if o else 0)(ref()),
            kinds=("resumed", "refused"))

        # --------------------------------------- journal + ownership lock
        self.journal: Optional[StateFile] = None
        self._resume_phase: Optional[str] = None
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            self.journal = StateFile(
                os.path.join(state_dir, "controller.journal"),
                point="controller.journal", plane="pipeline")
            prior = self.journal.read()
            if prior:
                self._adopt_prior(prior)
        self._journal_write()  # claim ownership (or commit a fresh one)

        # ------------------------------------------------ status endpoint
        self.status_server = None
        if status_port is not None:
            from deeplearning4j_tpu.scaleout.statetracker import \
                InMemoryStateTracker
            from deeplearning4j_tpu.scaleout.status import StatusServer

            self.status_server = StatusServer(
                InMemoryStateTracker(), port=status_port,
                extra=lambda: (lambda o: o.status() if o else {})(ref()),
                health=lambda: (lambda o: {
                    "ok": True, "phase": o.phase,
                    "pipeline": o.name} if o else {"ok": False})(ref()))
            self.status_server.start()

    # ------------------------------------------------- journal / adoption
    def _owner_fingerprint(self) -> dict:
        pid = os.getpid()
        return {"pid": pid, "start_time": procs.proc_start_time(pid)}

    def _adopt_prior(self, prior: dict) -> None:
        """Restart over a prior journal: refuse while its owner still
        lives (double-start lock), else resume its decision state —
        champion, quarantine list, and any promotion in flight."""
        owner = prior.get("owner")
        if owner and owner.get("pid"):
            verdict = procs.classify_pid(owner["pid"],
                                         owner.get("start_time"))
            if verdict == "adopted":  # alive AND fingerprint-matched
                self._m_adoptions["refused"].inc()
                raise ControllerBusy(
                    f"deployment controller journal {self.journal.path} "
                    f"is owned by live pid {owner['pid']} — refusing to "
                    "double-start on one checkpoint dir")
        self._m_restarts.inc()
        self.incarnation = int(prior.get("incarnation", 0)) + 1
        self.champion = prior.get("champion")
        self.quarantined = dict(prior.get("quarantined") or {})
        phase = prior.get("phase", IDLE)
        cand = prior.get("candidate")
        if cand and phase in (CANARY, PROMOTING, ROLLING_BACK):
            # an in-flight decision: re-drive it to its verdict before
            # looking at anything newer (run_once resumes it first)
            self.candidate = cand
            self._resume_phase = phase
            self.phase = phase
            self._m_adoptions["resumed"].inc()

    def _journal_write(self) -> None:
        if self.journal is None:
            return
        self.journal.try_write({
            "plane": "pipeline",
            "controller": self.name,
            "incarnation": self.incarnation,
            "owner": self._owner_fingerprint(),
            "phase": self.phase,
            "champion": self.champion,
            "candidate": self.candidate,
            "quarantined": self.quarantined,
            "checkpoint_dir": os.path.abspath(self.checkpoint_dir),
            "written_at": time.time(),
        })

    # --------------------------------------------------------- quarantine
    def _quarantine_marker(self, step: int) -> str:
        return os.path.join(self.checkpoint_dir,
                            ckfmt.step_dir_name(step), QUARANTINE_MARKER)

    def _is_quarantined(self, step: int) -> bool:
        if str(step) in self.quarantined:
            return True
        try:
            return os.path.exists(self._quarantine_marker(step))
        except OSError:
            return False

    def _quarantine(self, cand: dict, reason: str) -> None:
        """Commit the rejection: QUARANTINED marker in the step dir
        (atomic rename — the negative COMMITTED) + journaled reason.
        A step dir the writer already pruned still lands in the
        journal's quarantine list, so the verdict survives either
        way."""
        step = cand.get("step")
        self.quarantined[str(step)] = reason
        self._m_quarantines.inc()
        marker = self._quarantine_marker(step)
        try:
            tmp = marker + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"step": step, "reason": reason,
                           "at": time.time(),
                           "metrics": cand.get("metrics")}, f)
            os.replace(tmp, marker)
        except OSError as e:
            log.warning("could not write %s (%s); quarantine survives "
                        "in the journal", marker, e)
        self._journal_write()

    # ------------------------------------------------------------- watch
    def _scan(self) -> Optional[dict]:
        """One bounded poll of the checkpoint dir: newest COMMITTED,
        non-quarantined step beyond the champion, or None."""
        chaos.hit("pipeline.watch", dir=self.checkpoint_dir)
        steps = list_committed_steps(self.checkpoint_dir)
        for s in steps:
            if s not in self._seen:
                self._seen.add(s)
                self._m_seen.inc()
        champ_step = ((self.champion or {}).get("step")
                      if self.champion else None)
        eligible = [s for s in steps
                    if not self._is_quarantined(s)
                    and (champ_step is None or s > champ_step)]
        if not eligible:
            return None
        step = max(eligible)
        return {"path": os.path.abspath(self.checkpoint_dir),
                "step": step, "metrics": None}

    # --------------------------------------------------------- eval gate
    def _gate(self, cand: dict) -> Optional[dict]:
        """Run the eval gate. Returns the candidate (with metrics) on
        pass; None on fail (quarantined) or on an eval that could not
        run (left pending — NOT a failed eval)."""
        if self.eval_data is None:
            return cand  # unarmed gate: the canary probe decides
        self.phase = EVALUATING
        self.candidate = cand
        self._journal_write()
        t0 = time.perf_counter()
        try:
            chaos.hit("pipeline.eval", step=cand["step"])
            from deeplearning4j_tpu.eval.holdout import evaluate_checkpoint

            metrics = evaluate_checkpoint(
                cand["path"], self.eval_data,
                label_columns=self.label_columns, step=cand["step"])
        except (chaos.ChaosError, ckfmt.CheckpointError, OSError,
                ValueError) as e:
            # the candidate may have been pruned mid-eval, the holdout
            # file unreadable, or a chaos fault fired: pending, retried
            # next poll — never quarantined for an eval that didn't run
            log.warning("eval gate could not run for step %s: %s",
                        cand.get("step"), e)
            self.phase = IDLE
            self.candidate = None
            self._journal_write()
            return None
        self._m_eval_s.observe(time.perf_counter() - t0)
        cand = {**cand, "metrics": metrics}
        score = metrics.get(self.metric)
        champ_metrics = (self.champion or {}).get("metrics") or {}
        champ_score = champ_metrics.get(self.metric)
        if self.eval_via_fleet and self.champion is not None:
            # regression baseline from the LIVE fleet, scored on the
            # batch tier (docs/SERVING.md "Priority tiers") so the
            # gate's bulk traffic sheds first and never preempts a
            # user; an unreachable/shedding fleet falls back to the
            # journaled champion score — an eval that could not run
            # must not change the verdict's inputs silently
            try:
                from deeplearning4j_tpu.eval.holdout import \
                    evaluate_via_fleet

                live = evaluate_via_fleet(
                    self.fleet_url, self.eval_data,
                    label_columns=self.label_columns,
                    timeout=self.request_timeout)
                if live.get(self.metric) is not None:
                    champ_score = live[self.metric]
            except Exception as e:
                log.warning(
                    "live champion baseline unavailable (%s); using "
                    "journaled score %s", e, champ_score)
        if score is None:
            verdict = f"metric {self.metric!r} missing from eval output"
        elif score < self.eval_threshold:
            verdict = (f"{self.metric}={score:.4f} below absolute "
                       f"threshold {self.eval_threshold}")
        elif (champ_score is not None
                and score < champ_score - self.regression_margin):
            verdict = (f"{self.metric}={score:.4f} regressed more than "
                       f"{self.regression_margin} below champion "
                       f"{champ_score:.4f} (step "
                       f"{(self.champion or {}).get('step')})")
        else:
            self._m_eval_pass.inc()
            return cand
        self._m_eval_fail.inc()
        log.info("eval gate rejected step %s: %s", cand["step"], verdict)
        self._quarantine(cand, f"eval_gate: {verdict}")
        self.phase = IDLE
        self.candidate = None
        self._journal_write()
        return None

    # ----------------------------------------------------------- promote
    def _drive_reload(self, path: str, step: Optional[int]):
        """Ask the fleet to canary-reload onto (path, step). Returns
        (result_dict, definitive): definitive=False means the fleet
        never reached a verdict (unreachable / no ready replicas /
        reload already in flight) — the candidate stays pending."""
        champ = self.champion or {}
        if self.fleet is not None:
            from deeplearning4j_tpu.serving.errors import OverloadedError
            from deeplearning4j_tpu.serving.fleet import NoReadyReplicas

            try:
                res = self.fleet.rolling_reload(
                    path, step=step,
                    rollback_path=champ.get("path"),
                    rollback_step=champ.get("step"),
                    probe=self.probe, model_id=self.model_id)
                return res, True
            except (NoReadyReplicas, OverloadedError) as e:
                return {"reloaded": False, "error": str(e)}, False
        import urllib.error
        import urllib.request

        payload = {"path": path, "step": step,
                   "rollback_path": champ.get("path"),
                   "rollback_step": champ.get("step"),
                   "probe": self.probe}
        if self.model_id is not None:
            payload["model_id"] = self.model_id
        req = urllib.request.Request(
            self.fleet_url + "/reload",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout) as r:
                return json.loads(r.read()), True
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                data = json.loads(body)
            except ValueError:
                data = {"error": body.decode(errors="replace")}
            # 409 is the router's definitive "canary failed, rolled
            # back" verdict; 5xx (no ready replicas, shedding) is infra
            return data, e.code == 409
        except Exception as e:
            return {"reloaded": False,
                    "error": f"{type(e).__name__}: {e}"}, False

    def _promote(self, cand: dict) -> dict:
        """Drive the canary promotion of an eval-passed candidate to
        its all-or-nothing verdict."""
        self.phase = CANARY
        self.candidate = cand
        self._journal_write()
        t0 = time.perf_counter()
        try:
            chaos.hit("pipeline.promote", step=cand.get("step"))
        except chaos.ChaosError as e:
            # fault before the fleet was touched: candidate pending,
            # fleet untouched on the old champion
            self.phase = IDLE
            self._journal_write()
            return {"action": "promote", "promoted": False,
                    "pending": True, "error": str(e)}
        result, definitive = self._drive_reload(cand["path"],
                                                cand.get("step"))
        if result.get("reloaded"):
            # verdict reached: journal PROMOTING before the champion
            # switch so a crash between the two re-drives to the same
            # (idempotent) outcome
            self.phase = PROMOTING
            self._journal_write()
            self.champion = cand
            self.candidate = None
            self.phase = IDLE
            self._m_promotions.inc()
            self._m_promote_s.observe(time.perf_counter() - t0)
            self._journal_write()
            log.info("promoted step %s to champion", cand.get("step"))
            return {"action": "promote", "promoted": True,
                    "step": cand.get("step")}
        if not definitive:
            self.phase = IDLE
            self._journal_write()
            return {"action": "promote", "promoted": False,
                    "pending": True, "error": result.get("error")}
        # definitive canary failure: the fleet already rolled itself
        # back (Fleet.rolling_reload's all-or-nothing contract) — commit
        # our half of the verdict
        self.phase = ROLLING_BACK
        self._journal_write()
        self._m_rollbacks.inc()
        reason = json.dumps(result.get("error") or result,
                            default=str)[:500]
        self._quarantine(cand, f"canary: {reason}")
        self.candidate = None
        self.phase = IDLE
        self._m_promote_s.observe(time.perf_counter() - t0)
        self._journal_write()
        log.info("canary for step %s failed; rolled back and "
                 "quarantined", cand.get("step"))
        return {"action": "promote", "promoted": False,
                "rolled_back": True, "step": cand.get("step"),
                "error": result}

    def _resume(self) -> Optional[dict]:
        """Finish the decision a prior incarnation died inside."""
        phase, cand = self._resume_phase, self.candidate
        self._resume_phase = None
        if not cand:
            return None
        if phase in (CANARY, PROMOTING):
            log.info("resuming in-flight promotion of step %s "
                     "(journaled phase %s)", cand.get("step"), phase)
            return self._promote(cand)
        if phase == ROLLING_BACK:
            # the failure verdict was already decided: re-assert the
            # champion on the fleet, then finish the quarantine
            champ = self.champion or {}
            if champ.get("path"):
                self._drive_reload(champ["path"], champ.get("step"))
            self._m_rollbacks.inc()
            self._quarantine(cand, "canary: rollback resumed after "
                                   "controller restart")
            self.candidate = None
            self.phase = IDLE
            self._journal_write()
            return {"action": "resume_rollback",
                    "step": cand.get("step")}
        return None

    # --------------------------------------------------------- main loop
    def run_once(self) -> dict:
        """One conveyor cycle: resume any journaled in-flight decision,
        scan, gate, promote. Returns a dict describing what happened
        (tests drive the controller deterministically through this)."""
        if self._resume_phase is not None:
            out = self._resume()
            if out is not None:
                return out
        try:
            cand = self._scan()
        except (chaos.ChaosError, OSError) as e:
            log.warning("checkpoint scan failed (retrying next poll): "
                        "%s", e)
            return {"action": "watch", "error": str(e)}
        if cand is None:
            return {"action": "idle"}
        gated = self._gate(cand)
        if gated is None:
            return {"action": "eval", "step": cand["step"],
                    "promoted": False}
        return self._promote(gated)

    def run(self, max_cycles: Optional[int] = None) -> None:
        """Poll forever (or `max_cycles`) at `poll_interval`, until
        `stop()`. This is what `cli pipeline` (under `cli watchdog`)
        blocks in."""
        cycles = 0
        while not self._stop.is_set():
            self.run_once()
            cycles += 1
            if max_cycles is not None and cycles >= max_cycles:
                return
            self._stop.wait(self.poll_interval)

    def stop(self) -> None:
        self._stop.set()

    def close(self, release: bool = True) -> None:
        """Stop polling and the status endpoint. `release=True` writes
        a final journal with no owner so a successor may start
        immediately; the decision state (champion, quarantine list)
        stays committed for it to adopt."""
        self.stop()
        if self.status_server is not None:
            self.status_server.stop()
        if self.journal is not None and release:
            state = self.journal.read() or {}
            state.update({
                "plane": "pipeline", "controller": self.name,
                "incarnation": self.incarnation, "owner": None,
                "phase": self.phase, "champion": self.champion,
                "candidate": self.candidate,
                "quarantined": self.quarantined,
                "checkpoint_dir": os.path.abspath(self.checkpoint_dir),
                "written_at": time.time(),
            })
            self.journal.try_write(state)

    @property
    def status_address(self):
        """StatusServer URL ("http://host:port"), None when unarmed."""
        return (self.status_server.address
                if self.status_server is not None else None)

    def __enter__(self) -> "DeploymentController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------- observability
    def status(self) -> dict:
        """The /stats-style surface (StatusServer `extra` hook)."""
        return {
            "pipeline": self.name,
            "phase": self.phase,
            "checkpoint_dir": os.path.abspath(self.checkpoint_dir),
            "champion": self.champion,
            "candidate": self.candidate,
            "quarantined": dict(self.quarantined),
            "incarnation": self.incarnation,
            "eval_threshold": self.eval_threshold,
            "eval_via_fleet": self.eval_via_fleet,
            "regression_margin": self.regression_margin,
            "metric": self.metric,
            "poll_interval": self.poll_interval,
            "fleet": (self.fleet_url if self.fleet_url
                      else getattr(self.fleet, "label", "in-process")),
            "counters": {
                "candidates_seen": int(self._m_seen.value),
                "eval_pass": int(self._m_eval_pass.value),
                "eval_fail": int(self._m_eval_fail.value),
                "promotions": int(self._m_promotions.value),
                "rollbacks": int(self._m_rollbacks.value),
                "quarantines": int(self._m_quarantines.value),
            },
            "uptime_s": round(time.time() - self.started_at, 3),
        }
