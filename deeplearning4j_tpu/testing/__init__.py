"""Test-and-drill support that ships IN the package (not under tests/):
the chaos fault-injection layer lives here because production modules
carry its injection points and spawned replica processes must be able
to import it (`DL4J_TPU_CHAOS` env activation, docs/FAULT_TOLERANCE.md).
"""

from deeplearning4j_tpu.testing import chaos  # noqa: F401
