"""Seeded, deterministic fault injection with named injection points.

The checkpoint writer grew an ad-hoc `between_files` crash hook; every
other drill in docs/FAULT_TOLERANCE.md and docs/FLEET.md injected its
fault by hand (kill a process here, close a socket there). This module
generalizes that into ONE registry the whole stack shares: production
code calls `chaos.hit("point.name")` at its injection points (a no-op
costing one attribute load while no plan is active), and a test, soak,
or bench activates a `ChaosPlan` — a seeded schedule of `Rule`s — to
make named points misbehave deterministically.

Injection points shipped today (`POINTS` below): socket faults on the
serving HTTP front end (accept-then-hang, slow-loris-shaped delays,
mid-stream reset on `/generate`), IO faults in the sharded checkpoint
writer (shard write / atomic-rename errors — the `between_files` drill,
generalized), and numeric faults (NaN-poisoned host batches feeding the
training guardian's non-finite defense). Process faults (SIGKILL /
SIGSTOP for hung replicas / SIGCONT) don't need an in-process point —
the `sigstop`/`sigcont`/`sigkill` helpers act on `ReplicaSpawner`
processes from the driving test or bench (`bench.py chaos`).

Determinism and replay: each rule draws from its OWN `random.Random`
seeded by `(plan.seed, rule index, point)`, and fires against the
POINT-LOCAL hit ordinal — so a rule's schedule depends only on the plan
spec and how many times its point was hit, never on other rules or
points. Every firing is recorded (`plan.log()`); `plan.replay_rules()`
converts a recorded schedule into exact-ordinal `at=` rules, so a
failing randomized soak replays bit-for-bit from its failure log.

Per-process activation: spawned replica servers participate by env —
`ReplicaSpawner(env={**os.environ, **chaos.env_spec(rules, seed=7)})`
serializes the plan into `DL4J_TPU_CHAOS`, and the child process
activates it on first `hit()`. Every firing also counts into the
`dl4j_chaos_injected{point=,kind=}` telemetry series, so a drill's /metrics
scrape shows exactly what was injected (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

__all__ = ["ChaosError", "ChaosReset", "Rule", "ChaosPlan", "POINTS",
           "KINDS", "ENV_VAR", "activate", "deactivate", "active",
           "configure", "hit", "maybe_nan", "env_spec",
           "sigstop", "sigcont", "sigkill"]

ENV_VAR = "DL4J_TPU_CHAOS"

#: rule kinds — "error"/"reset" raise at the point ("reset" asks the
#: site for a hard connection reset), "hang"/"delay" sleep there, "nan"
#: asks the site to poison its array (`maybe_nan`)
KINDS = ("error", "hang", "delay", "reset", "nan")

#: the named injection points production code carries today. hit() on a
#: name outside this table still works (new sites register by use);
#: the table is the documentation contract (docs/FAULT_TOLERANCE.md).
POINTS = {
    "server.accept": "serving HTTP front end, before any POST route "
                     "runs (accept-then-hang, errors before a reply)",
    "server.read": "after the request body is slurped (slow-loris-"
                   "shaped handler delays)",
    "server.predict": "before /predict admission into the batcher",
    "server.generate": "before /generate admission into the decode loop",
    "generate.midstream": "between streamed /generate chunks (in-band "
                          "error or hard socket reset mid-stream)",
    "decode.step": "decode loop, at the top of every scheduler pass "
                   "(tick) — a delay rule paces decode itself so SLO "
                   "drills can hold slot occupancy open; an error "
                   "fails every in-flight stream loudly",
    "decode.fork": "decode loop's copy-on-write page fork, after the "
                   "destination page is claimed (possibly by evicting "
                   "a cached prefix page) but before the device copy "
                   "— drills prove mid-fork faults leave pool-page "
                   "accounting balanced",
    "router.forward": "fleet router, before forwarding to a replica",
    "router.stream_resume": "fleet router, before each mid-stream "
                            "/generate failover attempt (after a "
                            "replica died/hung with the stream "
                            "partially delivered, before the "
                            "continuation is re-admitted on a "
                            "survivor — error = a resume that "
                            "fails, driving the bounded-attempts/"
                            "in-band-error fallback)",
    "checkpoint.write": "before each checkpoint shard file write",
    "checkpoint.rename": "before each atomic rename publish "
                         "(manifest, COMMITTED marker)",
    "train.batch": "host training batch before H2D (NaN poison "
                   "feeding the guardian's non-finite defense)",
    "worker.spawn": "supervised training worker entrypoint, before it "
                    "registers (error = spawn crash, delay = slow boot "
                    "— exercises the supervisor's respawn/backoff)",
    "worker.step": "supervised training worker, before each job's fit "
                   "(hang = hung-but-heartbeating worker for the "
                   "progress watermark, delay = deterministic "
                   "straggler, error = job failure/retry)",
    "worker.heartbeat": "supervised training worker's progress "
                        "reporter, before each progress line (hang/"
                        "delay silence the telemetry plane)",
    "worker.reconnect": "supervised training worker's supervisor-"
                        "reconnect loop, before each rejoin attempt "
                        "after the control plane vanished (error = "
                        "a worker that fails to rejoin and exits; "
                        "delay = slow re-announce)",
    "supervisor.journal": "training control-plane journal "
                          "(utils/statefile.py), fired with op=write "
                          "before the tmp write and op=rename before "
                          "the commit rename — an injected error at "
                          "ANY ordinal leaves the previous committed "
                          "journal in place (crash-atomicity drills)",
    "fleet.journal": "serving control-plane journal (the fleet/router "
                     "twin of supervisor.journal; same write/rename "
                     "ordinals and atomicity contract)",
    "fleet.kv_ship": "fleet KV page shipping (serving/fleetkv.py), "
                     "fired with role=export on the donor before its "
                     "pinned pages are read out, and role=fetch on "
                     "the receiver before it dials the donor — an "
                     "error/reset/hang ANYWHERE here must leave the "
                     "receiver falling back to plain prefill with a "
                     "bit-identical stream and both pools' page "
                     "accounting balanced (a hang on the export side "
                     "holds the donor's pins open, proving eviction "
                     "cannot consume a page mid-serialization)",
    "fleet.kv_summary": "replica affinity-summary build, before the "
                        "trie heads are hashed for /readyz — a fault "
                        "here degrades the replica to no-affinity "
                        "placement, never to unready",
    "compile.cache_write": "persistent AOT program store "
                           "(compilecache/store.py), fired with "
                           "op=write before the tmp entry write and "
                           "op=rename before the commit rename — an "
                           "error at ANY ordinal loses only that cache "
                           "entry (the process keeps its compiled "
                           "program; the next boot recompiles), and a "
                           "torn write is CRC-quarantined, never "
                           "loaded (docs/WARMUP.md)",
    "compile.cache_read": "persistent AOT program store, before each "
                          "entry read at load time — an error degrades "
                          "that program to a plain cold compile, "
                          "never a serve/train failure",
    "pipeline.watch": "deployment controller's checkpoint-directory "
                      "scan, before each poll's committed-step listing "
                      "(errors = an unreadable checkpoint root the "
                      "watcher must survive and retry)",
    "pipeline.eval": "deployment controller's eval gate, before the "
                     "held-out evaluation of a candidate runs (errors "
                     "leave the candidate pending — an eval that could "
                     "not run is NOT a failed eval, docs/PIPELINE.md)",
    "pipeline.promote": "deployment controller, before the canary "
                        "rolling reload is driven (errors mid-decision "
                        "leave the fleet on exactly one champion — the "
                        "journal resumes the promotion)",
    "controller.journal": "deployment controller journal (the deploy-"
                          "plane twin of supervisor.journal; same "
                          "write/rename ordinals and atomicity "
                          "contract)",
}


class ChaosError(RuntimeError):
    """An injected fault (kind="error"). Sites let it propagate like
    any real failure — that is the point."""


class ChaosReset(ChaosError):
    """An injected hard-reset (kind="reset"): the site should abort its
    connection abruptly (RST, not FIN) — a ChaosError for sites without
    a socket to reset."""


class Rule:
    """One fault rule bound to one injection point.

    `prob` fires per point-hit from the rule's own seeded RNG; `times`
    caps total firings; `after` skips the first N hits; `at` (explicit
    hit ordinals) overrides prob/after — the replay mechanism. `delay_s`
    sizes "delay" sleeps, `hang_s` sizes "hang" (default: effectively
    forever on request timescales)."""

    def __init__(self, point: str, kind: str, *, prob: float = 1.0,
                 times: Optional[int] = None, after: int = 0,
                 at: Optional[Sequence[int]] = None,
                 delay_s: float = 0.05, hang_s: float = 3600.0,
                 message: Optional[str] = None):
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r} "
                             f"(have {KINDS})")
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        self.point = str(point)
        self.kind = kind
        self.prob = float(prob)
        self.times = None if times is None else int(times)
        self.after = int(after)
        self.at = None if at is None else frozenset(int(i) for i in at)
        self.delay_s = float(delay_s)
        self.hang_s = float(hang_s)
        self.message = message

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {"point": self.point, "kind": self.kind}
        if self.prob != 1.0:
            out["prob"] = self.prob
        if self.times is not None:
            out["times"] = self.times
        if self.after:
            out["after"] = self.after
        if self.at is not None:
            out["at"] = sorted(self.at)
        if self.delay_s != 0.05:
            out["delay_s"] = self.delay_s
        if self.hang_s != 3600.0:
            out["hang_s"] = self.hang_s
        if self.message is not None:
            out["message"] = self.message
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Rule":
        return cls(**d)

    def __repr__(self) -> str:
        return f"Rule({self.to_dict()!r})"


class ChaosPlan:
    """A seeded set of rules plus the firing log.

    Thread-safe: concurrent hits serialize on one lock, and each point
    keeps its own hit ordinal — a rule's decision for (point, ordinal)
    is a pure function of the plan spec, so a recorded log replays
    exactly (`replay_rules`) even when the original run was driven by
    concurrent request threads."""

    def __init__(self, rules: Sequence[Union[Rule, dict]],
                 seed: int = 0):
        self.seed = int(seed)
        self.rules: List[Rule] = [
            r if isinstance(r, Rule) else Rule.from_dict(r)
            for r in rules]
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._fired = [0] * len(self.rules)
        self._log: List[dict] = []
        self._started = time.monotonic()
        # one RNG per rule, seeded by (plan seed, rule index, point):
        # rule i's draw for its point's n-th hit never depends on other
        # rules, other points, or wall-clock interleaving
        self._rngs = [random.Random(f"{self.seed}:{i}:{r.point}")
                      for i, r in enumerate(self.rules)]

    # ------------------------------------------------------- decisions
    def decide(self, point: str) -> Optional[Rule]:
        """Advance `point`'s hit ordinal and return the first rule that
        fires for it (or None). Called by `hit()`."""
        with self._lock:
            n = self._hits.get(point, 0)
            self._hits[point] = n + 1
            for i, rule in enumerate(self.rules):
                if rule.point != point:
                    continue
                if rule.times is not None and self._fired[i] >= rule.times:
                    continue
                if rule.at is not None:
                    fire = n in rule.at
                else:
                    if n < rule.after:
                        continue
                    # draw even at prob 1.0: the RNG stream position
                    # stays a function of the ordinal alone
                    draw = self._rngs[i].random()
                    fire = draw < rule.prob or rule.prob >= 1.0
                if fire:
                    self._fired[i] += 1
                    self._log.append({
                        "point": point, "kind": rule.kind, "hit": n,
                        "rule": i,
                        "t_s": round(time.monotonic() - self._started,
                                     4)})
                    return rule
            return None

    # ------------------------------------------------------ inspection
    def log(self) -> List[dict]:
        """Every firing so far (point, kind, point-local hit ordinal,
        rule index) — the failure log a soak prints on assert."""
        with self._lock:
            return [dict(e) for e in self._log]

    def hits(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hits)

    def fired(self) -> int:
        with self._lock:
            return sum(self._fired)

    def replay_rules(self) -> List[Rule]:
        """Rules that reproduce this plan's recorded schedule exactly:
        each original rule becomes an `at=` rule pinned to the ordinals
        it fired on. `ChaosPlan(plan.replay_rules())` fires the same
        faults at the same hits, whatever the seed."""
        by_rule: Dict[int, List[int]] = {}
        for entry in self.log():
            by_rule.setdefault(entry["rule"], []).append(entry["hit"])
        out = []
        for i, ords in sorted(by_rule.items()):
            src = self.rules[i]
            out.append(Rule(src.point, src.kind, at=ords,
                            delay_s=src.delay_s, hang_s=src.hang_s,
                            message=src.message))
        return out

    def spec(self) -> dict:
        """JSON-serializable plan spec (the `DL4J_TPU_CHAOS` payload)."""
        return {"seed": self.seed,
                "rules": [r.to_dict() for r in self.rules]}


# ------------------------------------------------------- process faults
def _pid(proc) -> int:
    return proc if isinstance(proc, int) else proc.pid


def sigstop(proc) -> None:
    """Freeze a replica process (hung-but-TCP-alive: the kernel keeps
    accepting connections into the listen backlog, the process never
    answers — the failure mode the circuit breaker exists for)."""
    os.kill(_pid(proc), signal.SIGSTOP)


def sigcont(proc) -> None:
    """Thaw a SIGSTOP'd process (the recovery half of the drill)."""
    os.kill(_pid(proc), signal.SIGCONT)


def sigkill(proc) -> None:
    """Hard-kill (the crash fault the fleet's eviction drills use)."""
    os.kill(_pid(proc), signal.SIGKILL)


# ---------------------------------------------------- module activation
_active: Optional[ChaosPlan] = None
_env_checked = False
_state_lock = threading.Lock()
_counters: Dict[Tuple[str, str], Any] = {}


def active() -> Optional[ChaosPlan]:
    """The live plan, bootstrapping from `DL4J_TPU_CHAOS` once (how a
    spawned replica process joins a drill)."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        with _state_lock:
            if not _env_checked:
                _env_checked = True
                raw = os.environ.get(ENV_VAR)
                if raw:
                    spec = json.loads(raw)
                    _active = ChaosPlan(spec.get("rules", []),
                                        seed=spec.get("seed", 0))
    return _active


def activate(plan: ChaosPlan) -> ChaosPlan:
    global _active
    with _state_lock:
        _active = plan
    return plan


def deactivate() -> Optional[ChaosPlan]:
    """Deactivate and return the plan (its log survives for replay)."""
    global _active, _env_checked
    with _state_lock:
        plan, _active = _active, None
        _env_checked = True  # an explicit deactivate beats the env
    return plan


def configure(rules: Sequence[Union[Rule, dict]],
              seed: int = 0) -> ChaosPlan:
    """Build and activate a plan in one call (tests/soaks)."""
    return activate(ChaosPlan(rules, seed=seed))


def env_spec(rules: Sequence[Union[Rule, dict]],
             seed: int = 0) -> Dict[str, str]:
    """Env-var dict that activates this plan in a spawned process:
    `ReplicaSpawner(env={**os.environ, **chaos.env_spec(...)})`."""
    return {ENV_VAR: json.dumps(ChaosPlan(rules, seed=seed).spec())}


def _count(point: str, kind: str) -> None:
    key = (point, kind)
    c = _counters.get(key)
    if c is None:
        # lazy import: chaos must stay import-light (checkpoint/serving
        # both pull it in) and never cycle with telemetry
        from deeplearning4j_tpu import telemetry

        c = telemetry.get_registry().counter(
            "dl4j_chaos_injected",
            "faults injected by the chaos layer").labels(
                point=point, kind=kind)
        _counters[key] = c
    c.inc()


# -------------------------------------------------------------- the hook
def hit(point: str, **ctx) -> Optional[str]:
    """The injection point. No active plan: returns None (one global
    load + compare). Otherwise the first matching rule acts here —
    "error"/"reset" raise, "hang"/"delay" sleep — and the kind is
    returned for site-handled kinds ("nan", and "reset" sites that
    catch `ChaosReset`)."""
    plan = _active if _env_checked else active()
    if plan is None:
        return None
    rule = plan.decide(point)
    if rule is None:
        return None
    _count(point, rule.kind)
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return "delay"
    if rule.kind == "hang":
        time.sleep(rule.hang_s)
        return "hang"
    if rule.kind == "reset":
        raise ChaosReset(
            rule.message or f"chaos: injected reset at {point}")
    if rule.kind == "error":
        raise ChaosError(
            rule.message or f"chaos: injected error at {point}")
    return rule.kind  # "nan": the site corrupts via maybe_nan


def maybe_nan(point: str, arr, **ctx):
    """Numeric-fault site helper: returns `arr` NaN-poisoned (a copy)
    when a "nan" rule fires at `point`, else `arr` untouched. Only
    float arrays are poisoned — the guardian's non-finite defense is
    the downstream consumer (docs/FAULT_TOLERANCE.md)."""
    if (_active if _env_checked else active()) is None:
        return arr
    if hit(point, **ctx) != "nan":
        return arr
    import numpy as np

    arr = np.array(arr, copy=True)
    if not np.issubdtype(arr.dtype, np.floating):
        return arr
    flat = arr.reshape(-1)
    flat[: max(1, flat.size // 8)] = np.nan
    return arr
