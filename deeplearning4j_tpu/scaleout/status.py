"""Live run-state status endpoint for distributed training.

Parity: the reference's state tracker embeds a Dropwizard status web UI
on :8080/8180 (BaseHazelCastStateTracker.java:181-189) exposing cluster
state while a run is in flight; the word-vector scatter app rides a
sibling server (nlp/plot/dropwizard/RenderApplication.java:37 — our
plot/render_server.py covers that one).

TPU-native design: a tiny stdlib ThreadingHTTPServer owned by the master
process (the tracker is pure control plane, SURVEY §2.8) on the shared
utils/httpd.py `ServerHandle` lifecycle (graceful shutdown releases the
listening socket — serving/server.py and plot/render_server.py migrated
in PR 3; this server now rides the same helper), serving

- ``GET /status.json`` — machine-readable snapshot: workers with
  heartbeat ages, in-flight jobs, pending updates, counters, KV keys,
  wave progress (when attached to a runtime), early-stop state, plus
  server uptime + package version;
- ``GET /healthz`` — liveness: ok / uptime_s / version;
- ``GET /metrics`` — Prometheus text exposition of the process-global
  telemetry registry (``/snapshot`` is the JSON twin) — the same
  catalogue the serving front end exposes, docs/OBSERVABILITY.md;
- ``GET /`` — a self-contained HTML view that polls the JSON.

The server never blocks training: every read takes the tracker's lock
only long enough to copy primitive state.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from deeplearning4j_tpu.telemetry import exposition
from deeplearning4j_tpu.utils.httpd import ServerHandle

_PAGE = """<!doctype html>
<html><head><title>deeplearning4j-tpu run status</title>
<style>
 body { font-family: monospace; margin: 2em; }
 table { border-collapse: collapse; margin: 1em 0; }
 td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
 h2 { margin: 0.5em 0 0 0; font-size: 1em; }
</style></head>
<body>
<h1>run status</h1>
<div id="root">loading…</div>
<script>
function row(k, v) {
  return "<tr><td>" + k + "</td><td>" + JSON.stringify(v) + "</td></tr>";
}
function table(obj) {
  return "<table>" + Object.entries(obj).map(
    ([k, v]) => row(k, v)).join("") + "</table>";
}
async function tick() {
  const r = await fetch("status.json");
  const s = await r.json();
  let html = "";
  for (const [section, body] of Object.entries(s)) {
    html += "<h2>" + section + "</h2>";
    html += (body !== null && typeof body === "object" && !Array.isArray(body))
      ? table(body) : "<p>" + JSON.stringify(body) + "</p>";
  }
  document.getElementById("root").innerHTML = html;
}
tick(); setInterval(tick, 1000);
</script></body></html>
"""


def _jsonable(value: Any) -> Any:
    """Clamp tracker values to JSON-safe primitives (arrays and arbitrary
    objects are summarized, not serialized)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    shape = getattr(value, "shape", None)
    if shape is not None:
        return f"<array shape={tuple(shape)}>"
    return f"<{type(value).__name__}>"


def snapshot(tracker, runtime=None,
             extra: Optional[Callable[[], Dict[str, Any]]] = None,
             started_at: Optional[float] = None) -> Dict[str, Any]:
    """One coherent status snapshot of a tracker (and optionally the
    master runtime driving it). `started_at` (the owning server's start
    time) adds uptime; the package version always rides along so a
    fleet scrape can tell which build each master runs."""
    from deeplearning4j_tpu import __version__

    now = time.time()
    heartbeats = tracker.heartbeats()
    state: Dict[str, Any] = {
        "now": now,
        "workers": {
            w: {"heartbeat_age_s": round(now - hb, 3)}
            for w, hb in heartbeats.items()
        },
        "jobs_in_flight": sorted(j.worker_id for j in tracker.jobs()),
        "pending_updates": sorted(tracker.worker_updates()),
        "counters": _jsonable(tracker.counters()),
        "has_current_model": tracker.get_current() is not None,
        "early_stop": {
            "best_loss": _jsonable(tracker.best_loss()),
            "patience": tracker.patience(),
            "tripped": tracker.early_stop(),
        },
        "batch_size": tracker.batch_size(),
        "done": tracker.is_done(),
        "server": {
            "version": __version__,
            **({"uptime_s": round(now - started_at, 3)}
               if started_at is not None else {}),
        },
    }
    stale = tracker.stale_workers(now)
    if stale:
        state["stale_workers"] = sorted(stale)
    if runtime is not None:
        state["waves"] = {
            "completed": getattr(runtime, "waves", None),
            "open_wave_size": getattr(runtime, "_wave_size", None),
            "orphan_jobs": len(getattr(runtime, "_orphan_jobs", []) or []),
            "n_workers": getattr(runtime, "n_workers", None),
        }
    if extra is not None:
        state["extra"] = _jsonable(extra())
    return state


class StatusServer:
    """Serve `snapshot` over HTTP from a daemon thread (the Dropwizard
    status-UI equivalent, BaseHazelCastStateTracker.java:181-189), on
    the shared utils/httpd.py ServerHandle lifecycle. The socket binds
    at construction (so `address` is valid before `start()`); the serve
    thread runs between start() and stop()."""

    def __init__(self, tracker, runtime=None, host: str = "127.0.0.1",
                 port: int = 0,
                 extra: Optional[Callable[[], Dict[str, Any]]] = None,
                 health: Optional[Callable[[], Dict[str, Any]]] = None):
        self.tracker = tracker
        self.runtime = runtime
        self.extra = extra
        #: optional readiness verdict merged into /healthz: a dict whose
        #: "ok" key decides the status code (False -> 503). The training
        #: supervisor wires its quorum check here so a fleet scrape (or a
        #: cluster manager) sees quorum loss as unhealthy, not merely as
        #: a status.json detail.
        self.health = health
        self.started_at = time.time()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path in ("/status.json", "/status"):
                    try:
                        body = json.dumps(snapshot(
                            outer.tracker, outer.runtime, outer.extra,
                            started_at=outer.started_at)).encode()
                        ctype = "application/json"
                        code = 200
                    except Exception as e:  # surface, don't kill the thread
                        body = json.dumps({"error": repr(e)}).encode()
                        ctype = "application/json"
                        code = 500
                elif self.path.startswith(("/healthz", "/metrics",
                                           "/snapshot")):
                    # same surface-don't-kill contract as /status.json:
                    # a rendering error must answer 500, not reset the
                    # scraper's connection
                    try:
                        if self.path.startswith("/healthz"):
                            from deeplearning4j_tpu import __version__

                            verdict = (_jsonable(outer.health())
                                       if outer.health is not None else {})
                            payload = {
                                "ok": bool(verdict.get("ok", True)),
                                "uptime_s": round(
                                    time.time() - outer.started_at, 3),
                                "version": __version__,
                            }
                            payload.update(
                                {k: v for k, v in verdict.items()
                                 if k != "ok"})
                            body = json.dumps(payload).encode()
                            ctype = "application/json"
                            code = 200 if payload["ok"] else 503
                        else:
                            _, ctype, body = exposition.handle_metrics_get(
                                self.path)
                            code = 200
                    except Exception as e:
                        body = json.dumps({"error": repr(e)}).encode()
                        ctype = "application/json"
                        code = 500
                elif self.path == "/":
                    body = _PAGE.encode()
                    ctype = "text/html; charset=utf-8"
                    code = 200
                else:
                    body = b"not found"
                    ctype = "text/plain"
                    code = 404
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        server = ThreadingHTTPServer((host, port), _Handler)
        thread = threading.Thread(
            target=server.serve_forever, name="status-server", daemon=True)
        self.handle = ServerHandle(server, thread)
        self.host, self.port = self.handle.host, self.handle.port

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "StatusServer":
        self.started_at = time.time()
        self.handle.thread.start()
        return self

    def stop(self) -> None:
        """Graceful: stop serving, release the socket, join the serve
        thread (ServerHandle.close)."""
        self.handle.close()
