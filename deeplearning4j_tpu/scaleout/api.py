"""The scaleout API: the distributable-work contract.

Parity: reference deeplearning4j-scaleout-api (SURVEY §2.2) —
`Job` (…/scaleout/job/Job.java:24: {work, result, workerId}),
`JobIterator`/`JobIteratorFactory` (…/scaleout/job/),
`WorkerPerformer` (…/scaleout/perform/WorkerPerformer.java:
perform/update/setup), `JobAggregator` (…/scaleout/aggregator/),
`WorkRouter`/`BaseWorkRouter` (…/api/workrouter/: sendWork gate + routeJob),
`UpdateSaver` (…/api/statetracker/UpdateSaver.java: off-heap persistence of
pending updates).

These are deliberately plain-Python host-side objects: on TPU the heavy
parameter exchange rides XLA collectives (parallel/), so the scaleout layer
only moves small control records and (for parameter-averaging parity mode)
packed parameter vectors between host threads/processes.
"""

from __future__ import annotations

import os
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


@dataclass
class Job:
    """Unit of distributable work (reference Job.java:24). `seq` is the
    job's position in the run's job stream (assigned at dispatch) — the
    stable identity that survives eviction/re-serve, so aggregation can
    fold updates in a canonical order and resume audits can account for
    every batch exactly once."""

    work: Any
    worker_id: str
    result: Any = None
    retries: int = 0
    seq: Optional[int] = None

    def __repr__(self):
        return (f"Job(worker_id={self.worker_id!r}, seq={self.seq}, "
                f"has_result={self.result is not None})")


class JobIterator:
    """Stream of Jobs bound to worker ids (reference JobIterator)."""

    def next(self, worker_id: str) -> Job:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def position(self) -> int:
        """Jobs consumed so far (the resume cursor)."""
        raise NotImplementedError

    def seek(self, position: int) -> None:
        """Jump the stream to `position` jobs consumed — how a resumed
        master skips the work a crashed run already aggregated
        (checkpoints record it as iterator_position; reference analog:
        re-reading the HDFS batch offset after ModelSavingActor
        restore)."""
        raise NotImplementedError


class CollectionJobIterator(JobIterator):
    """Iterate a fixed collection of work items
    (reference CollectionJobIterator)."""

    def __init__(self, items: List[Any]):
        self.items = list(items)
        self._pos = 0
        self._lock = threading.Lock()

    def next(self, worker_id: str) -> Job:
        with self._lock:
            if self._pos >= len(self.items):
                raise StopIteration
            item = self.items[self._pos]
            self._pos += 1
        return Job(work=item, worker_id=worker_id)

    def has_next(self) -> bool:
        with self._lock:
            return self._pos < len(self.items)

    def reset(self) -> None:
        with self._lock:
            self._pos = 0

    def position(self) -> int:
        with self._lock:
            return self._pos

    def seek(self, position: int) -> None:
        if not 0 <= position <= len(self.items):
            raise ValueError(f"seek({position}) outside 0..{len(self.items)}")
        with self._lock:
            self._pos = position


class DataSetJobIterator(JobIterator):
    """Wrap a DataSetIterator as a stream of mini-batch jobs (the reference's
    BatchActor pattern: each wave hands the next mini-batch to a worker,
    akka BatchActor.java:72-160)."""

    def __init__(self, dataset_iterator):
        self.it = dataset_iterator
        self._iter: Optional[Iterator] = None
        self._pending: Optional[Any] = None
        self._consumed = 0
        self._lock = threading.Lock()

    def _ensure(self):
        if self._iter is None:
            self.it.reset()
            self._iter = iter(self.it)

    def next(self, worker_id: str) -> Job:
        with self._lock:
            self._ensure()
            if self._pending is not None:
                ds, self._pending = self._pending, None
            else:
                ds = next(self._iter)
            self._consumed += 1
            return Job(work=ds, worker_id=worker_id)

    def has_next(self) -> bool:
        with self._lock:
            self._ensure()
            if self._pending is not None:
                return True
            try:
                self._pending = next(self._iter)
                return True
            except StopIteration:
                return False

    def reset(self) -> None:
        with self._lock:
            self.it.reset()
            self._iter = iter(self.it)
            # drop any batch has_next() prefetched from the OLD pass —
            # leaking it would also put position() off by one, and an
            # overshooting cursor makes a later resume skip a batch
            self._pending = None
            self._consumed = 0

    def position(self) -> int:
        with self._lock:
            return self._consumed

    def seek(self, position: int) -> None:
        """Reset the wrapped DataSetIterator and drain `position`
        batches — batch streams have no random access, so the resume
        cursor replays the prefix (cheap: host-side iteration only)."""
        with self._lock:
            self.it.reset()
            self._iter = iter(self.it)
            self._pending = None
            self._consumed = 0
            for _ in range(position):
                try:
                    next(self._iter)
                except StopIteration:
                    raise ValueError(
                        f"seek({position}) past end of dataset stream"
                    ) from None
                self._consumed += 1


class WorkerPerformer:
    """Pluggable compute (reference WorkerPerformer.java): `perform(job)`
    fills job.result; `update(*args)` installs new global state;
    `setup(conf)` wires from a config dict."""

    def perform(self, job: Job) -> None:
        raise NotImplementedError

    def update(self, *args: Any) -> None:
        raise NotImplementedError

    def setup(self, conf: Dict[str, Any]) -> None:
        raise NotImplementedError


class JobAggregator:
    """Reduce worker results (reference JobAggregator/WorkAccumulator)."""

    def accumulate(self, job: Job) -> None:
        raise NotImplementedError

    def aggregate(self) -> Any:
        raise NotImplementedError


class WorkRouter:
    """Policy for when/where work is sent (reference WorkRouter/
    BaseWorkRouter: sendWork gate + routeJob)."""

    WORK_ROUTER = "work_router"  # config key parity

    #: True = barrier-style waves (aggregate when all workers report);
    #: False = async/hogwild (merge updates as they arrive, send_work()
    #: gates each dispatch). Subclasses declare their semantics here.
    synchronous: bool = True

    def __init__(self, state_tracker):
        self.tracker = state_tracker

    def send_work(self) -> bool:
        raise NotImplementedError

    def route_job(self, job: Job) -> None:
        self.tracker.add_job(job)


class IterativeReduceWorkRouter(WorkRouter):
    """Synchronous DP: dispatch the next wave only when every registered
    worker has reported its update (reference
    IterativeReduceWorkRouter.java:46-57)."""

    def send_work(self) -> bool:
        workers = self.tracker.workers()
        if not workers:
            return False
        return len(self.tracker.worker_updates()) >= len(workers)


class HogWildWorkRouter(WorkRouter):
    """Asynchronous DP: always send — lock-free hogwild-style updates
    (reference HogWildWorkRouter.java:44-47)."""

    synchronous = False

    def send_work(self) -> bool:
        return True


class UpdateSaver:
    """Persistence for pending updates (reference UpdateSaver.java)."""

    def save(self, worker_id: str, update: Any) -> None:
        raise NotImplementedError

    def load(self, worker_id: str) -> Any:
        raise NotImplementedError

    def keys(self) -> List[str]:
        raise NotImplementedError

    def delete(self, worker_id: str) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class InMemoryUpdateSaver(UpdateSaver):
    def __init__(self):
        self._updates: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def save(self, worker_id, update):
        with self._lock:
            self._updates[worker_id] = update

    def load(self, worker_id):
        with self._lock:
            return self._updates.get(worker_id)

    def keys(self):
        with self._lock:
            return list(self._updates)

    def delete(self, worker_id):
        with self._lock:
            self._updates.pop(worker_id, None)

    def clear(self):
        with self._lock:
            self._updates.clear()


class LocalFileUpdateSaver(UpdateSaver):
    """Spill worker updates to local files keyed by worker id — updates
    accumulate on disk, not RAM (reference LocalFileUpdateSaver.java:36-120)."""

    def __init__(self, directory: Optional[str] = None):
        self.dir = directory or tempfile.mkdtemp(prefix="dl4j_tpu_updates_")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, worker_id: str) -> str:
        safe = worker_id.replace(os.sep, "_")
        return os.path.join(self.dir, f"{safe}.update.npy")

    def save(self, worker_id, update):
        with self._lock:
            with open(self._path(worker_id), "wb") as f:
                np.save(f, np.asarray(update), allow_pickle=False)

    def load(self, worker_id):
        path = self._path(worker_id)
        if not os.path.exists(path):
            return None
        with self._lock:
            with open(path, "rb") as f:
                return np.load(f, allow_pickle=False)

    def keys(self):
        with self._lock:
            return [f[:-len(".update.npy")] for f in os.listdir(self.dir)
                    if f.endswith(".update.npy")]

    def delete(self, worker_id):
        path = self._path(worker_id)
        with self._lock:
            if os.path.exists(path):
                os.unlink(path)

    def clear(self):
        with self._lock:
            for f in os.listdir(self.dir):
                if f.endswith(".update.npy"):
                    os.unlink(os.path.join(self.dir, f))


class WorkRetriever:
    """Per-worker dataset storage/retrieval — keeps job payloads OUT of
    the coordination plane so the tracker/RPC path carries only light
    job descriptors (reference WorkRetriever.java:33-62: save/load/clear/
    workers)."""

    def save(self, worker_id: str, job: "Job") -> None:
        raise NotImplementedError

    def load(self, worker_id: str) -> Optional["Job"]:
        raise NotImplementedError

    def clear(self, worker_id: str) -> None:
        raise NotImplementedError

    def workers(self) -> List[str]:
        raise NotImplementedError


class LocalWorkRetriever(WorkRetriever):
    """File-per-worker work store (reference LocalWorkRetriever.java) on
    any shared filesystem, using the no-pickle npz+JSON checkpoint codec
    so a shared work directory cannot execute code on read."""

    SUFFIX = ".work.bin"

    def __init__(self, directory: Optional[str] = None):
        self.dir = directory or tempfile.mkdtemp(prefix="dl4j_tpu_work_")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, worker_id: str) -> str:
        return os.path.join(self.dir,
                            worker_id.replace(os.sep, "_") + self.SUFFIX)

    def save(self, worker_id, job):
        # late imports: rpc/checkpoint depend on api's Job
        from deeplearning4j_tpu.scaleout.checkpoint import dump_payload
        from deeplearning4j_tpu.scaleout.rpc import _to_wire

        data = dump_payload(_to_wire(job))
        with self._lock:
            tmp = self._path(worker_id) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, self._path(worker_id))

    def load(self, worker_id):
        from deeplearning4j_tpu.scaleout.checkpoint import load_payload
        from deeplearning4j_tpu.scaleout.rpc import _from_wire

        path = self._path(worker_id)
        with self._lock:
            if not os.path.exists(path):
                return None
            with open(path, "rb") as f:
                return _from_wire(load_payload(f.read()))

    def clear(self, worker_id):
        with self._lock:
            path = self._path(worker_id)
            if os.path.exists(path):
                os.unlink(path)

    def workers(self):
        with self._lock:
            return [f[:-len(self.SUFFIX)] for f in os.listdir(self.dir)
                    if f.endswith(self.SUFFIX)]
