"""Cluster provisioning: bring worker hosts up and join them to a run.

Parity: reference deeplearning4j-aws —
- `HostProvisioner` (aws/ec2/provision/HostProvisioner.java:40-260: JSch
  ssh/scp `uploadAndRun` :96, `runRemoteCommand` :105,
  `uploadForDeployment` :154)
- `ClusterSetup` (aws/ec2/provision/ClusterSetup.java:40-120: create
  boxes, then provision every worker host in parallel with a setup
  script)
- `Ec2BoxCreator` (cloud instance creation) and
  `DistributedDeepLearningTrainer` (main).

TPU-native design: box creation lives in `scaleout/boxes.py`
(GceTpuBoxCreator drives the gcloud CLI; LocalBoxCreator is the embedded
tier) and these classes do what the reference does AFTER instances
exist: copy artifacts to each host and start the worker process.
Transports are pluggable: `LocalTransport` (same-host process spawn —
the test tier and single-host multi-process runs) and `SshTransport`
(OpenSSH subprocess — multi-host; keys/agent handled by ssh itself, no
password prompts, no embedded JSch-style crypto).
Workers join the run through the ConfigRegistry + launcher, so
provisioning only needs to start `python -m ...launcher worker` with the
registry root and run name.
"""

from __future__ import annotations

import logging
import os
import shlex
import shutil
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

__all__ = ["LocalTransport", "SshTransport", "HostProvisioner",
           "ClusterSetup"]


class Transport:
    """upload + run on one host."""

    def upload(self, local_path: str, remote_path: str) -> None:
        raise NotImplementedError

    def run(self, command: Sequence[str],
            detach: bool = False) -> Tuple[int, str]:
        """Run a command; returns (returncode, output). With detach=True
        the process is left running and (0, pid-string) returns
        immediately."""
        raise NotImplementedError


class LocalTransport(Transport):
    """Same-host transport: file copy + subprocess. The provisioning
    equivalent of the reference's embedded test tier."""

    def upload(self, local_path, remote_path):
        parent = os.path.dirname(remote_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        if os.path.abspath(local_path) == os.path.abspath(remote_path):
            return  # already in place (same-host deploy into its own dir)
        shutil.copy2(local_path, remote_path)

    def run(self, command, detach=False):
        if detach:
            proc = subprocess.Popen(
                list(command), stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True)
            return 0, str(proc.pid)
        proc = subprocess.run(list(command), capture_output=True, text=True)
        return proc.returncode, proc.stdout + proc.stderr


class SshTransport(Transport):
    """OpenSSH subprocess transport (reference HostProvisioner's JSch
    channel, minus embedded credentials — auth is ssh-agent/keyfile via
    standard ssh config)."""

    def __init__(self, host: str, user: Optional[str] = None,
                 port: int = 22, key_file: Optional[str] = None,
                 connect_timeout: int = 10):
        self.target = f"{user}@{host}" if user else host
        self.port = port
        self.key_file = key_file
        self.connect_timeout = connect_timeout

    def _ssh_base(self) -> List[str]:
        cmd = ["ssh", "-p", str(self.port),
               "-o", f"ConnectTimeout={self.connect_timeout}",
               "-o", "BatchMode=yes"]
        if self.key_file:
            cmd += ["-i", self.key_file]
        return cmd + [self.target]

    def upload(self, local_path, remote_path):
        cmd = ["scp", "-P", str(self.port), "-o", "BatchMode=yes"]
        if self.key_file:
            cmd += ["-i", self.key_file]
        cmd += [local_path, f"{self.target}:{remote_path}"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
        except FileNotFoundError as e:
            raise RuntimeError(
                f"scp to {self.target} failed: OpenSSH client not "
                f"installed ({e})") from e
        if proc.returncode != 0:
            raise RuntimeError(f"scp to {self.target} failed: {proc.stderr}")

    def run(self, command, detach=False):
        # each element shell-quoted: the remote side runs through a shell,
        # so paths/run-names with spaces or metacharacters must not split
        # or be interpreted (the detach path additionally wraps in nohup)
        remote = " ".join(shlex.quote(c) for c in command)
        if detach:
            remote = f"nohup {remote} >/dev/null 2>&1 & echo $!"
        try:
            proc = subprocess.run(self._ssh_base() + [remote],
                                  capture_output=True, text=True)
        except FileNotFoundError as e:
            return 127, f"ssh client not installed: {e}"
        return proc.returncode, proc.stdout + proc.stderr


class HostProvisioner:
    """Upload artifacts to one host and run commands there (reference
    HostProvisioner.java: uploadAndRun :96, runRemoteCommand :105,
    uploadForDeployment :154)."""

    def __init__(self, transport: Transport, host: str = "localhost"):
        self.transport = transport
        self.host = host

    def upload_for_deployment(self, local_path: str,
                              remote_path: str) -> None:
        self.transport.upload(local_path, remote_path)

    def run_remote_command(self, command: Sequence[str]) -> Tuple[int, str]:
        return self.transport.run(command)

    def upload_and_run(self, script_path: str, remote_dir: str = "",
                       interpreter: str = "bash") -> Tuple[int, str]:
        """Copy a setup script to the host and execute it (reference
        uploadAndRun :96)."""
        remote = os.path.join(remote_dir or ".",
                              os.path.basename(script_path))
        self.transport.upload(script_path, remote)
        return self.transport.run([interpreter, remote])


class ClusterSetup:
    """Provision every worker host in parallel and start launcher worker
    processes joined to one run (reference ClusterSetup.java:77-120
    provisionWorkers: one async provisioning task per host).

    `hosts` maps worker-id -> Transport. Box creation (Ec2BoxCreator) is
    the platform's job on TPU (gcloud/GKE); this starts at "hosts
    exist"."""

    def __init__(self, hosts: Dict[str, Transport],
                 registry_root: str, run_name: str,
                 setup_script: Optional[str] = None,
                 python: str = sys.executable):
        self.hosts = dict(hosts)
        self.registry_root = registry_root
        self.run_name = run_name
        self.setup_script = setup_script
        self.python = python
        self.results: Dict[str, Tuple[int, str]] = {}

    def _worker_command(self, worker_id: str) -> List[str]:
        return [self.python, "-m", "deeplearning4j_tpu.scaleout.launcher",
                "worker", "--registry", self.registry_root,
                "--run", self.run_name, "--worker-id", worker_id]

    def _provision_one(self, worker_id: str, transport: Transport,
                       detach: bool) -> None:
        try:
            prov = HostProvisioner(transport, host=worker_id)
            if self.setup_script:
                rc, out = prov.upload_and_run(self.setup_script)
                if rc != 0:
                    raise RuntimeError(f"setup script failed ({rc}): {out}")
            self.results[worker_id] = transport.run(
                self._worker_command(worker_id), detach=detach)
        except Exception as e:  # noqa: BLE001 — per-host isolation
            log.exception("provisioning %s failed", worker_id)
            self.results[worker_id] = (-1, str(e))

    def provision_workers(self, detach: bool = True) -> Dict[str, Tuple[int, str]]:
        """Parallel provisioning fan-out (reference provisionWorkers —
        Futures per host). Returns worker-id -> (rc, output/pid)."""
        threads = [
            threading.Thread(target=self._provision_one,
                             args=(wid, t, detach), daemon=True)
            for wid, t in self.hosts.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return dict(self.results)
