"""Supervised training-worker entrypoint.

The process half of `scaleout/supervisor.py`: joins a registered run
(same ConfigRegistry/RemoteStateTracker bootstrap as
`scaleout/launcher.py`), then runs the worker loop with the supervisor's
two extra planes wired in:

- a **progress socket** back to the supervisor (`progress_address` in
  the run config): one long-lived TCP connection carrying NDJSON lines
  — a hello announcing `(worker_id, pid, start_time, performed,
  last_seq)`, then `{"performed", "job_s", "last_seq"}` after every job
  plus periodic idle beats from a dedicated reporter thread. The
  supervisor heartbeats the tracker on the worker's behalf while this
  socket is OPEN (kernel-held counts: that is the point — a SIGSTOP'd
  worker "heartbeats" until the progress watermark catches it); the
  worker itself never calls `tracker.heartbeat`.
- **chaos points** (`testing/chaos.py`, activated per process via
  `DL4J_TPU_CHAOS` in the spawn env): `worker.spawn` before
  registration, `worker.step` before each job's fit, `worker.heartbeat`
  before each progress line, and `worker.reconnect` before each rejoin
  attempt — so hang/delay/error schedules are seeded and replayable
  per worker.

Losing the supervisor is NOT fatal (docs/FAULT_TOLERANCE.md "Who
watches the watcher"): a dropped tracker connection or progress socket
sends the worker into a bounded-backoff **reconnect loop** — it
re-resolves the run from the registry (a restarted supervisor
incarnation re-registers the same run name with its new tracker and
progress addresses), reconnects both planes, and re-announces its
identity plus the last `Job.seq` it completed, so a restarted
supervisor re-adopts it WARM (its compiled train step survives). Any
in-flight job at crash time is abandoned un-published — the restarted
supervisor's journal+checkpoint cursor re-dispatches it, so no example
is lost or double-trained. Only after `reconnect_grace` seconds with no
supervisor returning does the worker exit cleanly.

Exit contract: clean exit when the master finishes (`is_done`), when
the run disappears from the registry, or when the reconnect grace
window expires with no supervisor; non-zero on a `worker.spawn` chaos
error or any bootstrap failure, which the supervisor turns into
eviction + respawn/backoff.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import threading
import time
from typing import Optional

from deeplearning4j_tpu.scaleout.launcher import (PERFORMER_CLASS,
                                                  PERFORMER_CONF,
                                                  TRACKER_ADDRESS,
                                                  WORK_DIR,
                                                  _resolve_performer)
from deeplearning4j_tpu.scaleout.registry import ConfigRegistry
from deeplearning4j_tpu.scaleout.rpc import RemoteStateTracker
from deeplearning4j_tpu.scaleout.runtime import perform_job
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils import procs

log = logging.getLogger(__name__)


class _ProgressReporter:
    """Streams progress lines to the supervisor from its own thread —
    so a hung train step (chaos `worker.step` hang, a wedged device)
    keeps reporting idle beats while the performed-count stalls, which
    is exactly the hung-but-heartbeating shape the supervisor's
    watermark evicts.

    The hello line carries the worker's (pid, start_time) fingerprint
    and its cumulative (performed, last_seq) — a restarted supervisor
    incarnation uses the fingerprint to verify/adopt the process and
    the counters to reconstruct per-worker progress state."""

    def __init__(self, address: str, worker_id: str,
                 interval: float = 0.25, performed: int = 0,
                 last_seq: Optional[int] = None):
        host, port = address.rsplit(":", 1)
        self.worker_id = worker_id
        self.interval = float(interval)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=10.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self.performed = int(performed)
        self.last_job_s = None  # float | None
        self.last_seq = last_seq
        self._dirty = threading.Event()
        self._closed = threading.Event()
        #: the (pid, start_time) fingerprint rides EVERY line, not just
        #: the hello: a supervisor that dropped the hello (mid-init,
        #: restarting) must be able to judge adopt-or-kill from any
        #: later beat — an unfingerprinted stray could never be either
        self._fingerprint = {"pid": os.getpid(),
                             "start_time": procs.proc_start_time(
                                 os.getpid())}
        self._send(self._line())  # hello names + fingerprints the peer
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"progress-{worker_id}")
        self._thread.start()

    def _send(self, payload: dict) -> None:
        data = (json.dumps(payload) + "\n").encode()
        with self._lock:
            self._sock.sendall(data)

    def _line(self) -> dict:
        out = {"worker_id": self.worker_id, "performed": self.performed,
               **self._fingerprint}
        if self.last_job_s is not None:
            out["job_s"] = self.last_job_s
        if self.last_seq is not None:
            out["last_seq"] = int(self.last_seq)
        return out

    def _run(self) -> None:
        while not self._closed.is_set():
            self._dirty.wait(timeout=self.interval)
            self._dirty.clear()
            if self._closed.is_set():
                return
            try:
                chaos.hit("worker.heartbeat")
                self._send(self._line())
            except chaos.ChaosError:
                # injected reporter death: progress lines stop but the
                # socket stays OPEN — the hung-but-heartbeating shape
                return
            except OSError:
                # supervisor gone or connection severed: training
                # continues; liveness is the supervisor's call now
                return

    def report_job(self, job_s: float,
                   seq: Optional[int] = None) -> None:
        self.performed += 1
        self.last_job_s = float(job_s)
        if seq is not None:
            self.last_seq = int(seq)
        self._dirty.set()  # wake the reporter for an immediate line

    def close(self) -> None:
        self._closed.set()
        self._dirty.set()
        try:
            self._sock.close()
        except OSError:
            pass


class _Session:
    """One connected stint against one supervisor incarnation: the
    tracker RPC plus the progress reporter, torn down together."""

    def __init__(self, conf: dict, worker_id: str, performed: int,
                 last_seq: Optional[int]):
        self.tracker = RemoteStateTracker(conf[TRACKER_ADDRESS])
        self.reporter = None
        try:
            if conf.get("progress_address"):
                self.reporter = _ProgressReporter(
                    conf["progress_address"], worker_id,
                    performed=performed, last_seq=last_seq)
            # the first RPC doubles as the connectivity probe — and
            # (re-)registers us with whichever incarnation answered
            self.tracker.add_worker(worker_id)
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self.reporter is not None:
            self.reporter.close()
            self.reporter = None
        try:
            self.tracker.close()
        except Exception:
            pass


def run_supervised_worker(*, registry_root: str, run_name: str,
                          worker_id: str,
                          heartbeat_interval: float = 0.05,
                          registration_timeout: float = 30.0,
                          reconnect_grace: float = 30.0,
                          reconnect_backoff: float = 0.25) -> int:
    """Join a supervised run and work until the master finishes —
    surviving the master's own death for up to `reconnect_grace`
    seconds per outage. Returns the number of jobs performed."""
    chaos.hit("worker.spawn")  # error kind = spawn crash (respawn drill)
    registry = ConfigRegistry(registry_root)
    conf = registry.retrieve_run(run_name, timeout=registration_timeout)
    performer_cls = _resolve_performer(conf[PERFORMER_CLASS])
    performer = performer_cls()
    if conf.get(PERFORMER_CONF):
        performer.setup(conf[PERFORMER_CONF])
    retriever = None
    if conf.get(WORK_DIR):
        from deeplearning4j_tpu.scaleout.api import LocalWorkRetriever

        retriever = LocalWorkRetriever(conf[WORK_DIR])
    performed = 0
    last_seq: Optional[int] = None
    log.info("worker %s joined supervised run %s", worker_id, run_name)

    def work(session: _Session) -> None:
        """The job loop against one incarnation. Raises ConnectionError
        when that incarnation vanishes."""
        nonlocal performed, last_seq
        tracker = session.tracker
        if hasattr(performer, "bind_tracker"):
            performer.bind_tracker(tracker)
        while not tracker.is_done():
            if tracker.needs_replicate(worker_id):
                current = tracker.get_current()
                if current is not None:
                    performer.update(current)
                tracker.done_replicating(worker_id)
            job = tracker.job_for(worker_id)
            if job is None or job.result is not None:
                time.sleep(heartbeat_interval)
                continue
            # the chaos point runs INSIDE the timed window (via
            # before_perform): an injected delay models a slow step,
            # and the straggler stats must see it as one. The
            # execute/publish/bounded-retry contract is the ONE shared
            # implementation (runtime.perform_job); a ConnectionError
            # propagates to the reconnect loop below — the job it
            # interrupted is abandoned UN-PUBLISHED (the restarted
            # supervisor re-dispatches it from its journaled cursor,
            # so publishing it too would double-train the batch).
            t0 = time.perf_counter()
            if perform_job(tracker, worker_id, performer, job,
                           work_retriever=retriever,
                           before_perform=lambda j: chaos.hit(
                               "worker.step", worker=worker_id,
                               seq=j.seq)):
                performed += 1
                if job.seq is not None:
                    last_seq = int(job.seq)
                if session.reporter is not None:
                    session.reporter.report_job(
                        time.perf_counter() - t0, seq=job.seq)

    session: Optional[_Session] = None
    lost_at: Optional[float] = None
    backoff = reconnect_backoff
    try:
        while True:
            if session is None:
                # -------- (re)connect to whichever incarnation owns
                # the run now. The registry is the rendezvous: a
                # restarted supervisor re-registers the SAME run name
                # with fresh tracker/progress addresses.
                if lost_at is not None:
                    if (time.monotonic() - lost_at) >= reconnect_grace:
                        log.info(
                            "worker %s: no supervisor within %.1fs "
                            "grace, exiting cleanly", worker_id,
                            reconnect_grace)
                        break
                    try:
                        chaos.hit("worker.reconnect", worker=worker_id)
                    except chaos.ChaosError:
                        log.warning("worker %s: injected reconnect "
                                    "failure, exiting", worker_id)
                        break
                try:
                    conf = registry.retrieve_run(run_name)
                    session = _Session(conf, worker_id, performed,
                                       last_seq)
                except (KeyError, ConnectionError, OSError) as e:
                    # run not (re-)registered yet, or a stale config
                    # naming a dead incarnation: back off and retry
                    # within the grace window
                    if lost_at is None:
                        lost_at = time.monotonic()
                    log.debug("worker %s: reconnect attempt failed "
                              "(%s)", worker_id, e)
                    time.sleep(min(backoff, 2.0))
                    backoff = min(backoff * 2.0, 2.0)
                    continue
                if lost_at is not None:
                    log.info("worker %s: rejoined run %s after %.1fs "
                             "(performed=%d, last_seq=%s)", worker_id,
                             run_name, time.monotonic() - lost_at,
                             performed, last_seq)
                lost_at = None
                backoff = reconnect_backoff
            try:
                work(session)
                break  # is_done: the run finished — clean exit
            except ConnectionError as e:
                # master gone: NOT a shutdown anymore — enter the
                # bounded reconnect loop and survive a restart
                log.info("worker %s: master connection lost (%s); "
                         "reconnecting for up to %.1fs", worker_id, e,
                         reconnect_grace)
                session.close()
                session = None
                lost_at = time.monotonic()
                time.sleep(min(backoff, 2.0))
    finally:
        if session is not None:
            session.close()
    return performed


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.scaleout.worker",
        description="Supervised elastic-training worker process "
                    "(spawned by scaleout.supervisor.TrainingSupervisor)")
    p.add_argument("--registry", required=True,
                   help="ConfigRegistry root directory")
    p.add_argument("--run", required=True, help="run name to join")
    p.add_argument("--worker-id", required=True)
    p.add_argument("--heartbeat-interval", type=float, default=0.05)
    p.add_argument("--registration-timeout", type=float, default=30.0)
    p.add_argument("--reconnect-grace", type=float, default=30.0,
                   help="seconds to outlive a vanished supervisor: "
                        "retry the registry/tracker with backoff and "
                        "re-announce, then exit cleanly if no "
                        "incarnation returns")
    p.add_argument("--reconnect-backoff", type=float, default=0.25,
                   help="initial reconnect backoff (doubles, capped)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    performed = run_supervised_worker(
        registry_root=args.registry, run_name=args.run,
        worker_id=args.worker_id,
        heartbeat_interval=args.heartbeat_interval,
        registration_timeout=args.registration_timeout,
        reconnect_grace=args.reconnect_grace,
        reconnect_backoff=args.reconnect_backoff)
    log.info("worker %s done: %d jobs", args.worker_id, performed)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
