"""Supervised training-worker entrypoint.

The process half of `scaleout/supervisor.py`: joins a registered run
(same ConfigRegistry/RemoteStateTracker bootstrap as
`scaleout/launcher.py`), then runs the worker loop with the supervisor's
two extra planes wired in:

- a **progress socket** back to the supervisor (`progress_address` in
  the run config): one long-lived TCP connection carrying NDJSON lines
  — `{"worker_id"}` hello, then `{"performed", "job_s"}` after every
  job plus periodic idle beats from a dedicated reporter thread. The
  supervisor heartbeats the tracker on the worker's behalf while this
  socket is OPEN (kernel-held counts: that is the point — a SIGSTOP'd
  worker "heartbeats" until the progress watermark catches it); the
  worker itself never calls `tracker.heartbeat`.
- **chaos points** (`testing/chaos.py`, activated per process via
  `DL4J_TPU_CHAOS` in the spawn env): `worker.spawn` before
  registration, `worker.step` before each job's fit, and
  `worker.heartbeat` before each progress line — so hang/delay/error
  schedules are seeded and replayable per worker.

Exit contract: clean exit when the master finishes (`is_done`) or its
tracker connection drops (master gone == shutdown, the launcher's
convention); non-zero on a `worker.spawn` chaos error or any bootstrap
failure, which the supervisor turns into eviction + respawn/backoff.
"""

from __future__ import annotations

import argparse
import json
import logging
import socket
import threading
import time

from deeplearning4j_tpu.scaleout.launcher import (PERFORMER_CLASS,
                                                  PERFORMER_CONF,
                                                  TRACKER_ADDRESS,
                                                  WORK_DIR,
                                                  _resolve_performer)
from deeplearning4j_tpu.scaleout.registry import ConfigRegistry
from deeplearning4j_tpu.scaleout.rpc import RemoteStateTracker
from deeplearning4j_tpu.scaleout.runtime import perform_job
from deeplearning4j_tpu.testing import chaos

log = logging.getLogger(__name__)


class _ProgressReporter:
    """Streams progress lines to the supervisor from its own thread —
    so a hung train step (chaos `worker.step` hang, a wedged device)
    keeps reporting idle beats while the performed-count stalls, which
    is exactly the hung-but-heartbeating shape the supervisor's
    watermark evicts."""

    def __init__(self, address: str, worker_id: str,
                 interval: float = 0.25):
        host, port = address.rsplit(":", 1)
        self.worker_id = worker_id
        self.interval = float(interval)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=10.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self.performed = 0
        self.last_job_s = None  # float | None
        self._dirty = threading.Event()
        self._closed = threading.Event()
        self._send({"worker_id": worker_id})  # hello names the peer
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"progress-{worker_id}")
        self._thread.start()

    def _send(self, payload: dict) -> None:
        data = (json.dumps(payload) + "\n").encode()
        with self._lock:
            self._sock.sendall(data)

    def _line(self) -> dict:
        out = {"worker_id": self.worker_id, "performed": self.performed}
        if self.last_job_s is not None:
            out["job_s"] = self.last_job_s
        return out

    def _run(self) -> None:
        while not self._closed.is_set():
            self._dirty.wait(timeout=self.interval)
            self._dirty.clear()
            if self._closed.is_set():
                return
            try:
                chaos.hit("worker.heartbeat")
                self._send(self._line())
            except chaos.ChaosError:
                # injected reporter death: progress lines stop but the
                # socket stays OPEN — the hung-but-heartbeating shape
                return
            except OSError:
                # supervisor gone or connection severed: training
                # continues; liveness is the supervisor's call now
                return

    def report_job(self, job_s: float) -> None:
        self.performed += 1
        self.last_job_s = float(job_s)
        self._dirty.set()  # wake the reporter for an immediate line

    def close(self) -> None:
        self._closed.set()
        self._dirty.set()
        try:
            self._sock.close()
        except OSError:
            pass


def run_supervised_worker(*, registry_root: str, run_name: str,
                          worker_id: str,
                          heartbeat_interval: float = 0.05,
                          registration_timeout: float = 30.0) -> int:
    """Join a supervised run and work until the master finishes.
    Returns the number of jobs performed."""
    chaos.hit("worker.spawn")  # error kind = spawn crash (respawn drill)
    registry = ConfigRegistry(registry_root)
    conf = registry.retrieve_run(run_name, timeout=registration_timeout)
    tracker = RemoteStateTracker(conf[TRACKER_ADDRESS])
    performer_cls = _resolve_performer(conf[PERFORMER_CLASS])
    performer = performer_cls()
    if conf.get(PERFORMER_CONF):
        performer.setup(conf[PERFORMER_CONF])
    retriever = None
    if conf.get(WORK_DIR):
        from deeplearning4j_tpu.scaleout.api import LocalWorkRetriever

        retriever = LocalWorkRetriever(conf[WORK_DIR])
    reporter = None
    if conf.get("progress_address"):
        reporter = _ProgressReporter(conf["progress_address"], worker_id)
    performed = 0
    log.info("worker %s joined supervised run %s", worker_id, run_name)
    try:
        if hasattr(performer, "bind_tracker"):
            performer.bind_tracker(tracker)
        tracker.add_worker(worker_id)
        while not tracker.is_done():
            if tracker.needs_replicate(worker_id):
                current = tracker.get_current()
                if current is not None:
                    performer.update(current)
                tracker.done_replicating(worker_id)
            job = tracker.job_for(worker_id)
            if job is None or job.result is not None:
                time.sleep(heartbeat_interval)
                continue
            # the chaos point runs INSIDE the timed window (via
            # before_perform): an injected delay models a slow step,
            # and the straggler stats must see it as one. The
            # execute/publish/bounded-retry contract is the ONE shared
            # implementation (runtime.perform_job); a ConnectionError
            # propagates to the master-gone clean exit below.
            t0 = time.perf_counter()
            if perform_job(tracker, worker_id, performer, job,
                           work_retriever=retriever,
                           before_perform=lambda j: chaos.hit(
                               "worker.step", worker=worker_id,
                               seq=j.seq)):
                performed += 1
                if reporter is not None:
                    reporter.report_job(time.perf_counter() - t0)
    except ConnectionError as e:
        # master gone = shutdown signal (launcher.run_worker contract)
        log.info("worker %s: master connection lost (%s), exiting",
                 worker_id, e)
    finally:
        if reporter is not None:
            reporter.close()
        tracker.close()
    return performed


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.scaleout.worker",
        description="Supervised elastic-training worker process "
                    "(spawned by scaleout.supervisor.TrainingSupervisor)")
    p.add_argument("--registry", required=True,
                   help="ConfigRegistry root directory")
    p.add_argument("--run", required=True, help="run name to join")
    p.add_argument("--worker-id", required=True)
    p.add_argument("--heartbeat-interval", type=float, default=0.05)
    p.add_argument("--registration-timeout", type=float, default=30.0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    performed = run_supervised_worker(
        registry_root=args.registry, run_name=args.run,
        worker_id=args.worker_id,
        heartbeat_interval=args.heartbeat_interval,
        registration_timeout=args.registration_timeout)
    log.info("worker %s done: %d jobs", args.worker_id, performed)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
