"""Distributed NLP performers: Word2Vec / GloVe / WordCount jobs over the
scaleout runtime.

Parity: reference nlp/scaleout/perform —
`Word2VecPerformer` (Word2VecPerformer.java:88-140: train sentence jobs
against shared syn0/syn1, alpha decayed from the tracker's
NUM_WORDS_SO_FAR counter :91-:115, emit Word2VecResult DELTAS),
`GlovePerformer` (GlovePerformer.java + GloveWork/GloveResult: co-occurrence
batch jobs against shared w/c tables), and
`WordCountWorkPerformer` + `WordCountJobAggregator` (scaleout/perform/text/:
count words per job, Counter-merge aggregation).

TPU-native design: each job trains a BATCH on-device via the same jitted
steps the single-process models use (word2vec's HS/negative-sampling step,
glove's AdaGrad weighted-LSQ step); only packed table vectors and small
counters cross the control plane. Delta results (new - old tables) let the
master apply averaged deltas onto the current model, so concurrent workers
compose like the reference's hogwild-with-averaging instead of last-write-
wins.
"""

from __future__ import annotations

import logging
from collections import Counter
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.scaleout.api import Job, JobAggregator, WorkerPerformer

log = logging.getLogger(__name__)

#: tracker counter key (reference Word2VecPerformer.NUM_WORDS_SO_FAR)
NUM_WORDS_SO_FAR = "word2vec_num_words_so_far"


class Word2VecWorkPerformer(WorkerPerformer):
    """Train skip-gram on each job's sentence batch; result = table deltas.

    conf keys: `vocab` (VocabCache.to_dict()), `layer_size`, `window`,
    `negative`, `learning_rate`, `min_learning_rate`, `total_words`
    (expected corpus words x iterations, drives alpha decay), `sample`,
    `batch_pairs`, `seed`.
    """

    def __init__(self):
        self._w2v = None
        self._tracker = None
        self.alpha0 = 0.025
        self.min_alpha = 1e-4
        self.total_words = 1.0

    def bind_tracker(self, tracker) -> None:
        """Runtime hook: the live StateTracker drives alpha decay
        (reference Word2VecPerformer gets the tracker injected)."""
        self._tracker = tracker

    def setup(self, conf: Dict[str, Any]) -> None:
        from deeplearning4j_tpu.nlp.vocab import VocabCache
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        self._w2v = Word2Vec(
            layer_size=int(conf.get("layer_size", 100)),
            window=int(conf.get("window", 5)),
            negative=int(conf.get("negative", 0)),
            learning_rate=float(conf.get("learning_rate", 0.025)),
            min_learning_rate=float(conf.get("min_learning_rate", 1e-4)),
            sample=float(conf.get("sample", 0.0)),
            batch_pairs=int(conf.get("batch_pairs", 4096)),
            seed=int(conf.get("seed", 123)),
        )
        self._w2v.vocab = VocabCache.from_dict(conf["vocab"])
        from deeplearning4j_tpu.nlp.huffman import max_code_length
        self._w2v._code_len = max(1, max_code_length(self._w2v.vocab))
        self._w2v.reset_weights()
        self.alpha0 = self._w2v.alpha
        self.min_alpha = self._w2v.min_alpha
        self.total_words = float(conf.get(
            "total_words", self._w2v.vocab.total_word_count))
        self._step = None
        self._rng = np.random.RandomState(self._w2v.seed)

    # ------------------------------------------------------------- packing
    def _tables(self) -> Dict[str, Any]:
        t = {"syn0": self._w2v.syn0}
        if self._w2v.syn1 is not None:
            t["syn1"] = self._w2v.syn1
        if self._w2v.syn1neg is not None:
            t["syn1neg"] = self._w2v.syn1neg
        return t

    def pack(self) -> np.ndarray:
        return np.concatenate([np.asarray(v).ravel()
                               for _, v in sorted(self._tables().items())])

    def _install(self, packed: np.ndarray) -> None:
        import jax.numpy as jnp
        offset = 0
        for name, v in sorted(self._tables().items()):
            size = int(np.prod(np.asarray(v).shape))
            chunk = packed[offset:offset + size].reshape(np.asarray(v).shape)
            setattr(self._w2v, name, jnp.asarray(chunk))
            offset += size

    # ------------------------------------------------------------- perform
    def perform(self, job: Job) -> None:
        """job.work: list of sentences. Trains locally, result = delta."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp.sentence_iterator import (
            CollectionSentenceIterator)

        w2v = self._w2v
        if w2v is None:
            raise RuntimeError("setup() not called")
        if self._step is None:
            self._step, _ = w2v._build_step()  # per-batch step only
        sentences: List[str] = list(job.work)
        w2v.sentence_iter = CollectionSentenceIterator(sentences)

        before = self.pack()
        tables = self._tables()
        B = w2v.batch_pairs
        words_in_job = 0
        for centers, contexts, n_words in w2v._iter_pair_chunks(self._rng):
            words_in_job += n_words
            # alpha from the CLUSTER-WIDE words counter (reference :91)
            so_far = (self._tracker.count(NUM_WORDS_SO_FAR)
                      if self._tracker is not None else 0.0)
            alpha = max(self.min_alpha,
                        self.alpha0 * (1.0 - so_far / self.total_words))
            for lo in range(0, centers.size, B):
                bc, bx = centers[lo:lo + B], contexts[lo:lo + B]
                if bc.size < B:  # static batch shape
                    pad = np.arange(B - bc.size) % max(1, bc.size)
                    bc = np.concatenate([bc, bc[pad]])
                    bx = np.concatenate([bx, bx[pad]])
                w2v._key, k = jax.random.split(w2v._key)
                tables, _ = self._step(tables, jnp.asarray(bc),
                                       jnp.asarray(bx), jnp.float32(alpha), k)
        for name, v in tables.items():
            setattr(w2v, name, v)
        if self._tracker is not None and words_in_job:
            self._tracker.increment(NUM_WORDS_SO_FAR, float(words_in_job))
        job.result = self.pack() - before  # DELTA (reference Word2VecResult)

    def update(self, *args: Any) -> None:
        """Install the master's current packed tables."""
        self._install(np.asarray(args[0]))

    # convenience for tests / consumers
    def word_vectors(self):
        from deeplearning4j_tpu.nlp.word2vec import WordVectors
        return WordVectors(self._w2v.vocab, np.asarray(self._w2v.syn0))


class GloveWorkPerformer(WorkerPerformer):
    """Train GloVe on each job's co-occurrence triple batch; result = delta.

    conf keys: `vocab`, `layer_size`, `learning_rate`, `x_max`, `alpha`,
    `seed`. job.work: dict {rows, cols, vals} index arrays.
    """

    def __init__(self):
        self._params = None
        self._accum = None
        self._step = None
        self.conf: Dict[str, Any] = {}

    def setup(self, conf: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self.conf = dict(conf)
        v = len(conf["vocab"]["words"])
        d = int(conf.get("layer_size", 50))
        lr = float(conf.get("learning_rate", 0.05))
        x_max = float(conf.get("x_max", 100.0))
        alpha = float(conf.get("alpha", 0.75))
        key = jax.random.PRNGKey(int(conf.get("seed", 123)))
        kw, kc = jax.random.split(key)
        self._params = {
            "w": jax.random.uniform(kw, (v, d), jnp.float32, -0.5 / d,
                                    0.5 / d),
            "c": jax.random.uniform(kc, (v, d), jnp.float32, -0.5 / d,
                                    0.5 / d),
            "bw": jnp.zeros((v,), jnp.float32),
            "bc": jnp.zeros((v,), jnp.float32),
        }
        self._accum = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1e-8, jnp.float32), self._params)

        def loss_fn(params, r, c, x):
            wr, wc = params["w"][r], params["c"][c]
            pred = (jnp.sum(wr * wc, axis=1) + params["bw"][r]
                    + params["bc"][c])
            err = pred - jnp.log(x)
            fx = jnp.minimum(1.0, (x / x_max) ** alpha)
            return 0.5 * jnp.sum(fx * err * err) / r.shape[0]

        @jax.jit
        def step(params, accum, r, c, x):
            loss, grads = jax.value_and_grad(loss_fn)(params, r, c, x)
            accum = jax.tree_util.tree_map(lambda a, g: a + g * g, accum,
                                           grads)
            params = jax.tree_util.tree_map(
                lambda p, g, a: p - lr * g / jnp.sqrt(a), params, grads,
                accum)
            return params, accum, loss

        self._step = step

    def pack(self) -> np.ndarray:
        return np.concatenate([np.asarray(v).ravel()
                               for _, v in sorted(self._params.items())])

    def _install(self, packed: np.ndarray) -> None:
        import jax.numpy as jnp
        offset = 0
        for name in sorted(self._params):
            shape = self._params[name].shape
            size = int(np.prod(shape))
            self._params[name] = jnp.asarray(
                packed[offset:offset + size].reshape(shape))
            offset += size

    def perform(self, job: Job) -> None:
        import jax.numpy as jnp

        if self._step is None:
            raise RuntimeError("setup() not called")
        work = job.work
        before = self.pack()
        self._params, self._accum, loss = self._step(
            self._params, self._accum,
            jnp.asarray(np.asarray(work["rows"], np.int32)),
            jnp.asarray(np.asarray(work["cols"], np.int32)),
            jnp.asarray(np.asarray(work["vals"], np.float32)))
        job.result = self.pack() - before

    def update(self, *args: Any) -> None:
        self._install(np.asarray(args[0]))


class WordCountWorkPerformer(WorkerPerformer):
    """Count words in each job's sentence batch (reference
    WordCountWorkPerformer — the distributed vocab-building primitive)."""

    def __init__(self):
        from deeplearning4j_tpu.nlp.tokenization import (
            DefaultTokenizerFactory)
        self.tokenizer_factory = DefaultTokenizerFactory()

    def setup(self, conf: Dict[str, Any]) -> None:
        pass

    def perform(self, job: Job) -> None:
        counts: Counter = Counter()
        for sentence in job.work:
            counts.update(self.tokenizer_factory.tokenize(sentence))
        job.result = dict(counts)

    def update(self, *args: Any) -> None:
        pass


class WordCountJobAggregator(JobAggregator):
    """Counter-merge aggregation (reference WordCountJobAggregator): wave
    counts merge INTO the running totals held as the current model."""

    def __init__(self):
        self.counts: Counter = Counter()

    def accumulate(self, job: Job) -> None:
        if job.result:
            self.counts.update(job.result)

    def aggregate(self) -> Optional[Dict[str, float]]:
        return dict(self.counts) if self.counts else None

    @staticmethod
    def apply(current, aggregated) -> Dict[str, float]:
        merged = Counter(current or {})
        merged.update(aggregated)
        return dict(merged)


class DeltaAveragingAggregator(JobAggregator):
    """Average delta vectors; publication applies `current + mean(delta)`
    (reference Word2VecJobAggregator semantics over Word2VecResult)."""

    def __init__(self):
        self._sum: Optional[np.ndarray] = None
        self._n = 0

    def accumulate(self, job: Job) -> None:
        if job.result is None:
            return
        r = np.asarray(job.result, np.float64)
        self._sum = r if self._sum is None else self._sum + r
        self._n += 1

    def aggregate(self) -> Optional[np.ndarray]:
        if self._sum is None:
            return None
        return (self._sum / self._n).astype(np.float32)

    @staticmethod
    def apply(current, aggregated) -> np.ndarray:
        if current is None:
            # publishing a bare delta would replace every worker's init
            # with near-zero garbage on the first replication
            raise ValueError(
                "DeltaAveragingAggregator needs the runtime constructed "
                "with initial_params (deltas apply onto a current model)")
        return np.asarray(current) + aggregated
