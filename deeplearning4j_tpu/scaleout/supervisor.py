"""Self-healing elastic training: a process supervisor for DP workers.

PRs 7-8 made SERVING elastic (fleet heartbeats, circuit breakers, chaos
drills); a training run still died with any of its processes. This
module is the training-side mirror of that stack: a `TrainingSupervisor`
runs a data-parallel iterative-reduce job across N OUT-OF-PROCESS
workers (`scaleout/worker.py` entrypoints, spawned like
`serving/fleet.py`'s ReplicaSpawner — own session groups, module atexit
orphan sweep) and keeps the RUN alive across worker churn:

- **Liveness** rides the existing scaleout control plane: the
  supervisor heartbeats the `InMemoryStateTracker` on behalf of each
  worker for as long as the worker's PROGRESS SOCKET stays open
  (`_ProgressListener`), and `stale_workers()` drives eviction exactly
  as `runtime._evict_stale` always has. A SIGKILLed worker's socket
  closes (kernel FIN) -> heartbeats stop -> staleness evicts within the
  heartbeat window.
- **Hang detection** (the training twin of PR 8's circuit breaker): a
  SIGSTOP'd worker still HOLDS its TCP connection (the kernel keeps it
  ESTABLISHED), so liveness alone would trust it forever. The
  supervisor therefore also tracks a steps-per-heartbeat progress
  watermark — a worker holding a dispatched job whose performed-count
  has not advanced within `progress_timeout` is hung: evicted, its
  process group killed, its job re-served (orphan requeue).
- **Elastic respawn**: every eviction (crash, hang, straggler)
  schedules a replacement worker under a bounded respawn budget with
  exponential backoff; the wave barrier re-forms around the respawned
  member (`DistributedRuntime`'s exact-membership wave), and because
  updates fold in canonical job-seq order, the completed run's params
  are BIT-IDENTICAL to an uninterrupted run at the same wave schedule.
- **Elastic resume**: when capacity is durably lost (respawn budget
  exhausted, or a spawn that keeps failing), the supervisor restarts
  from the last COMMITTED sharded checkpoint resharded to the surviving
  topology: the checkpoint's params leaf is written as one shard per
  worker (`checkpoint/format.py` shard table), reassembled by
  `checkpoint/restore.py` whatever the survivor count, and the job
  stream seeks back to the checkpoint's cursor — no example is dropped
  or double-trained (`folded_seqs` is the audit trail).
- **Straggler defense**: per-job durations stream in on the progress
  plane; a worker persistently slower than the wave median by
  `straggler_factor` is flagged (telemetry + status), and after
  `straggler_strikes` consecutive flags evicted and respawned.

- **Crash-safe control plane** (`state_dir=`): the supervisor itself is
  no longer the one process nobody may lose. Every membership
  transition journals (pid + start-time fingerprint, slot, generation,
  progress port, incarnation) through a `utils/statefile.py` StateFile
  (`supervisor.journal`, the checkpoint layer's atomic-rename commit
  idiom), and a restarted incarnation **re-adopts** its live children
  instead of respawning them: journaled pids are fingerprint-verified
  (`utils/procs.pid_matches` — pid + /proc start time, never pid
  alone), surviving workers become `AdoptedProc` members that
  reconnect warm (`scaleout/worker.py`'s bounded-backoff reconnect
  loop re-announces `(worker_id, last Job.seq)`), the progress port is
  rebound from the journal, and run state restores from the last
  COMMITTED checkpoint so the completed run stays BIT-IDENTICAL with
  zero lost or double-trained examples. The failure ladder gains a
  rung above PR 9's: reconnect-adopt -> reshard-resume -> fresh start.
  A torn journal or dead children degrade one rung, never crash; a
  crash-exiting incarnation hands its children off
  (`procs.release_spawned` scopes the atexit sweep to what THIS
  incarnation still owns) and unknown rejoiners are adopted-or-killed,
  never leaked. `cli watchdog` supervises the supervisor.

Chaos points (`testing/chaos.py`, env-activated per worker process so
drills are seeded and replayable): `worker.spawn`, `worker.step`,
`worker.heartbeat`, `worker.reconnect`, and `supervisor.journal` (the
journal's write/rename ordinals) — see `WorkerSpawner(env_for=...)`
for per-worker plans. Telemetry: `dl4j_train_fleet_*`
(workers-by-state, evictions by reason, respawns, resumes, straggler
flags, wave latency histogram) plus `dl4j_controlplane_*` (restarts,
adoptions by kind, journal write/commit histograms, incarnation
gauge), scraped from the supervisor's StatusServer `/metrics`;
`status.json` carries per-worker lifecycle and `/healthz` answers 503
when quorum (`min_workers`) is lost. Runbook: docs/FAULT_TOLERANCE.md
"Who watches the watcher".
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.scaleout.launcher import MultiProcessMaster
from deeplearning4j_tpu.scaleout.runtime import JOBS_DROPPED
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.utils import procs
from deeplearning4j_tpu.utils.statefile import StateFile

__all__ = ["TrainingSupervisor", "WorkerSpawner", "SupervisedWorker",
           "SupervisorAbort", "STARTING", "RUNNING", "SUSPECT",
           "EVICTED", "DEAD"]

log = logging.getLogger(__name__)

#: worker lifecycle (the fleet's replica states, trained on training)
STARTING = "starting"   # spawned, progress socket not yet open
RUNNING = "running"     # connected and heartbeating
SUSPECT = "suspect"     # straggler-flagged, still in the wave
EVICTED = "evicted"     # removed from the run (respawn may replace it)
DEAD = "dead"           # evicted with no respawn capacity left
STATES = (STARTING, RUNNING, SUSPECT, EVICTED, DEAD)

_sup_seq = itertools.count()


class SupervisorAbort(RuntimeError):
    """The supervisor cannot keep the run alive (quorum lost and no
    respawn capacity). The failure ladder bottomed out:
    respawn -> reshard-resume -> abort (docs/FAULT_TOLERANCE.md)."""


# --------------------------------------------------------------- spawner
class WorkerSpawner:
    """Spawns local training-worker processes
    (`python -m deeplearning4j_tpu.scaleout.worker`) joined to a
    registered run. Single-host backend (tests/bench/laptop drills); a
    multi-host deployment brings its own process manager and launches
    the same entrypoint. `env_for(worker_id)` lets a drill hand ONE
    worker a chaos plan (`chaos.env_spec`) while its peers run clean —
    how seeded straggler/hang schedules stay per-process."""

    def __init__(self, registry_root: str, run_name: str, *,
                 env: Optional[dict] = None,
                 env_for: Optional[Callable[[str], dict]] = None,
                 python: Optional[str] = None,
                 heartbeat_interval: float = 0.05,
                 reconnect_grace: float = 30.0):
        self.registry_root = str(registry_root)
        self.run_name = run_name
        self.reconnect_grace = float(reconnect_grace)
        base_env = dict(env) if env is not None else dict(os.environ)
        # the package must be importable in the child whatever cwd the
        # supervisor runs from
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = base_env.get("PYTHONPATH", "")
        if pkg_root not in path.split(os.pathsep):
            base_env["PYTHONPATH"] = (pkg_root + (os.pathsep + path
                                                  if path else ""))
        # elastic respawns inherit the AOT program cache: a replacement
        # worker loads the fleet's train-step executables instead of
        # recompiling them (docs/WARMUP.md)
        from deeplearning4j_tpu import compilecache
        compilecache.export_env(base_env)
        self.env = base_env
        self.env_for = env_for
        self.python = python or sys.executable
        self.heartbeat_interval = float(heartbeat_interval)

    def command(self, worker_id: str) -> List[str]:
        return [self.python, "-m", "deeplearning4j_tpu.scaleout.worker",
                "--registry", self.registry_root,
                "--run", self.run_name,
                "--worker-id", worker_id,
                "--heartbeat-interval", str(self.heartbeat_interval),
                "--reconnect-grace", str(self.reconnect_grace)]

    def spawn(self, worker_id: str) -> subprocess.Popen:
        env = dict(self.env)
        if self.env_for is not None:
            env.update(self.env_for(worker_id) or {})
        proc = subprocess.Popen(
            self.command(worker_id), env=env, text=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True)
        procs.register_spawned(proc)
        return proc

    @staticmethod
    def stop(proc: subprocess.Popen, timeout: float = 10.0,
             term_first: bool = True) -> None:
        """Terminate a worker and its whole process group — the shared
        group-stop discipline (utils/procs.py; same as
        ReplicaSpawner.stop). `term_first=False` goes straight to
        SIGKILL: a hung or SIGSTOP'd worker never honors SIGTERM and
        its work is already requeued."""
        procs.stop_process_group(proc, timeout=timeout,
                                 term_first=term_first)


# -------------------------------------------------------- progress plane
class _ProgressListener:
    """The supervisor's liveness/progress socket.

    Each worker opens ONE TCP connection at startup (hello line naming
    its worker id) and streams NDJSON progress lines. The listener's
    per-connection reader drives two signals:

    - **liveness**: while the connection is OPEN — lines arriving OR
      merely an established socket — `on_alive(wid)` fires every poll,
      which the supervisor turns into `tracker.heartbeat`. This is
      deliberately TCP-held liveness: a SIGSTOP'd worker's socket stays
      ESTABLISHED (the kernel answers for it), so it keeps
      "heartbeating" — exactly the hung-but-TCP-alive failure mode the
      progress watermark exists to catch. EOF/reset (process death)
      ends liveness immediately.
    - **progress**: each line's `performed` count and `job_s` duration
      feed the watermark and the straggler stats via
      `on_progress(wid, data)`.
    """

    def __init__(self, on_alive, on_progress, on_gone,
                 host: str = "127.0.0.1", poll_s: float = 0.25,
                 port: int = 0):
        self.on_alive = on_alive
        self.on_progress = on_progress
        self.on_gone = on_gone
        self.poll_s = float(poll_s)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            # a restarted incarnation rebinds its journaled port so
            # surviving workers' reconnects land without a registry
            # round trip; if something else claimed it meanwhile, fall
            # back to an ephemeral port — workers re-resolve the fresh
            # address from the re-registered run config either way
            self._sock.bind((host, int(port)))
        except OSError:
            self._sock.bind((host, 0))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = threading.Event()
        self._conns: Dict[str, socket.socket] = {}
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="supervisor-progress-accept")
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True,
                             name="supervisor-progress-read").start()

    def _reader(self, conn: socket.socket) -> None:
        wid = None
        conn.settimeout(self.poll_s)
        buf = b""
        try:
            while not self._closed.is_set():
                try:
                    chunk = conn.recv(4096)
                except socket.timeout:
                    # open-but-silent: the kernel still owns an
                    # ESTABLISHED socket for this peer — liveness holds
                    if wid is not None:
                        self.on_alive(wid)
                    continue
                except OSError:
                    break
                if not chunk:
                    break  # EOF: the process is gone
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        data = json.loads(line)
                    except ValueError:
                        continue
                    if wid is None:
                        wid = str(data.get("worker_id", ""))
                        if not wid:
                            return
                        with self._lock:
                            self._conns[wid] = conn
                    self.on_alive(wid)
                    self.on_progress(wid, data)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if wid is not None:
                with self._lock:
                    if self._conns.get(wid) is conn:
                        self._conns.pop(wid, None)
                self.on_gone(wid)

    def drop(self, worker_id: str) -> None:
        """Sever an evicted worker's connection so its kernel-held
        socket can never heartbeat it back into the run."""
        with self._lock:
            conn = self._conns.pop(worker_id, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


# --------------------------------------------------------- worker record
class SupervisedWorker:
    """Supervisor-side record of one worker process (mutations under
    the supervisor's lock)."""

    def __init__(self, worker_id: str, slot: int,
                 proc: Optional[subprocess.Popen] = None,
                 generation: int = 0, adopted: bool = False):
        self.id = worker_id
        self.slot = slot                # stable index of the capacity slot
        self.generation = generation    # respawn count for this slot
        self.proc = proc
        self.state = STARTING
        self.adopted = adopted          # re-adopted from a prior incarnation
        #: /proc start-time fingerprint journaled next to the pid so the
        #: NEXT incarnation never adopts a recycled pid
        self.start_time = (getattr(proc, "start_time", None)
                           or (procs.proc_start_time(proc.pid)
                               if proc is not None else None))
        self.spawned_at = time.monotonic()
        self.connected = False
        self.performed = 0              # jobs completed (worker-reported)
        self.last_step = 0              # alias surfaced in status.json
        self.last_seq: Optional[int] = None  # re-announced on reconnect
        self.last_progress_t = time.monotonic()
        self.job_seen_t: Optional[float] = None  # current dispatch seen at
        self.job_seconds: deque = deque(maxlen=8)
        self.straggler_strikes = 0
        self.evicted_at: Optional[float] = None
        self.eviction_reason: Optional[str] = None

    def mean_job_s(self) -> Optional[float]:
        if not self.job_seconds:
            return None
        return sum(self.job_seconds) / len(self.job_seconds)

    def snapshot(self) -> dict:
        out = {"state": self.state, "slot": self.slot,
               "generation": self.generation,
               "last_step": self.last_step,
               "straggler_strikes": self.straggler_strikes}
        if self.adopted:
            out["adopted"] = True
        if self.last_seq is not None:
            out["last_seq"] = self.last_seq
        mean = self.mean_job_s()
        if mean is not None:
            out["mean_job_s"] = round(mean, 4)
        if self.proc is not None:
            out["pid"] = self.proc.pid
            out["proc_alive"] = self.proc.poll() is None
        if self.eviction_reason is not None:
            out["eviction_reason"] = self.eviction_reason
        return out


# ------------------------------------------------------------ supervisor
class TrainingSupervisor(MultiProcessMaster):
    """MultiProcessMaster that OWNS its worker processes: spawn, health,
    hang/straggler defense, bounded respawn, and checkpoint-backed
    elastic resume. The wave/aggregation choreography is inherited; the
    `_tick` hook injects supervision into every master poll."""

    def __init__(self, job_iterator, *, run_name: str, registry,
                 performer_class: str,
                 performer_conf: Optional[Dict[str, Any]] = None,
                 n_workers: int = 2,
                 spawner: Optional[WorkerSpawner] = None,
                 checkpoint_dir: Optional[str] = None,
                 save_every_waves: int = 1,
                 keep_checkpoints: int = 3,
                 resume: Optional[str] = None,
                 max_respawns: int = 3,
                 respawn_backoff_s: float = 0.25,
                 heartbeat_timeout: float = 3.0,
                 progress_timeout: float = 15.0,
                 startup_grace: float = 120.0,
                 straggler_factor: float = 4.0,
                 straggler_min_samples: int = 2,
                 straggler_strikes: int = 2,
                 min_workers: int = 1,
                 conf_json: Optional[str] = None,
                 host: str = "127.0.0.1",
                 status_port: Optional[int] = None,
                 heartbeat_interval: float = 0.02,
                 state_dir: Optional[str] = None,
                 **kw):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if not 1 <= min_workers <= n_workers:
            raise ValueError(
                f"need 1 <= min_workers <= n_workers, got "
                f"{min_workers}..{n_workers}")
        self.run_label = run_name
        self.members: Dict[str, SupervisedWorker] = {}
        self._sup_lock = threading.RLock()
        self.max_respawns = int(max_respawns)
        self.respawns_used = 0
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.progress_timeout = float(progress_timeout)
        self.startup_grace = float(startup_grace)
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_samples = int(straggler_min_samples)
        self.straggler_strikes = int(straggler_strikes)
        self.min_workers = int(min_workers)
        self.checkpoint_dir = checkpoint_dir
        self.saver = None
        self._resume_request = resume
        self._slot_seq = itertools.count()
        self._respawn_queue: List[dict] = []  # {slot, gen, not_before}
        self._last_waves_seen = 0
        self._waves_since_save = 0
        self._last_saved_step: Optional[int] = None
        self.resume_events: List[dict] = []
        self._capacity_lost_pending = False
        self._aborted: Optional[str] = None

        # ------------------------------------ crash-safe control plane
        self.state_dir = state_dir
        self.journal: Optional[StateFile] = None
        self.incarnation = 0
        self.adoption_events: List[dict] = []
        self._adopt_respawn: List[tuple] = []  # (slot, generation)
        self._journal_io_lock = threading.Lock()
        #: strays are only judged once journal adoption has run — a
        #: survivor reconnecting to the rebound progress port mid-init
        #: must wait for its journaled record, not be adopted twice
        self._adoption_done = False
        prior = None
        if state_dir is not None:
            self.journal = StateFile(
                os.path.join(state_dir, "supervisor.journal"),
                point="supervisor.journal")
            prior = self.journal.read()
            if prior is not None:
                self.incarnation = int(prior.get("incarnation", 0)) + 1
            elif self.journal.torn:
                # a torn journal means a prior incarnation existed but
                # its children are unknown: spawn fresh under the new
                # incarnation's namespace and adopt-or-kill whoever
                # re-announces on the progress plane
                self.incarnation = 1
        self._init_metrics()

        if checkpoint_dir is not None:
            from deeplearning4j_tpu.checkpoint.writer import \
                AsyncCheckpointWriter

            self.saver = AsyncCheckpointWriter(checkpoint_dir,
                                               keep=keep_checkpoints)
        self.save_every_waves_elastic = int(save_every_waves)

        self._progress = _ProgressListener(
            self._on_worker_alive, self._on_worker_progress,
            self._on_worker_gone, host=host,
            port=int((prior or {}).get("progress_port") or 0))

        super().__init__(
            job_iterator, run_name=run_name, registry=registry,
            performer_class=performer_class,
            performer_conf=performer_conf, n_workers=n_workers,
            host=host, conf_json=conf_json, status_port=status_port,
            status_extra=self._status_extra,
            status_health=self._health,
            tracker=InMemoryStateTracker(
                heartbeat_timeout=heartbeat_timeout),
            heartbeat_interval=heartbeat_interval,
            **kw)
        # workers read the progress address from the run config
        registry.register_run(run_name, {
            **registry.retrieve_run(run_name),
            "progress_address": self._progress.address,
        })
        self.spawner = spawner if spawner is not None else WorkerSpawner(
            getattr(registry, "root", "."), run_name)
        adopted_any = False
        if prior is not None:
            try:
                adopted_any = self._adopt_prior(prior)
            except Exception:
                # a journal that parses but carries an unexpected shape
                # (older/newer writer, hand edit) must degrade like a
                # torn one — fresh spawns + stray adopt-or-kill — never
                # crash the restart into the watchdog's restart budget
                log.exception(
                    "supervisor %s: journal adoption failed; falling "
                    "back to fresh spawns", self.run_label)
        if self._resume_request:
            self._apply_initial_resume(self._resume_request)
        elif (self.incarnation > 0 and self.checkpoint_dir is not None):
            # a restarted incarnation implies resume-if-any: the last
            # COMMITTED checkpoint is the run state the adopted (or
            # fresh) pool continues from — the reconnect-adopt rung of
            # the failure ladder degrades to exactly PR 9's elastic
            # resume when no one survived, and to a fresh start when
            # nothing committed
            self._apply_initial_resume("auto")
        if adopted_any and not self.resume_events:
            log.warning(
                "supervisor %s: incarnation %d adopted %d worker(s) "
                "with no committed checkpoint — continuing from fresh "
                "params (ladder rung: fresh start, warm processes)",
                self.run_label, self.incarnation,
                sum(1 for e in self.adoption_events
                    if e["kind"] == "adopted"))
        self._journal_write()
        self._adoption_done = True

    # ------------------------------------------------------- telemetry
    def _init_metrics(self) -> None:
        reg = telemetry.get_registry()
        lab = {"run": self.run_label}
        self._m_evictions = {
            reason: reg.counter(
                "dl4j_train_fleet_evictions",
                "training workers evicted, by reason").labels(
                    reason=reason, **lab)
            for reason in ("stale", "hung", "straggler", "spawn_failed")}
        self._m_respawns = reg.counter(
            "dl4j_train_fleet_respawns",
            "replacement training workers spawned").labels(**lab)
        self._m_resumes = {
            kind: reg.counter(
                "dl4j_train_fleet_resumes",
                "elastic resumes from the last committed checkpoint, "
                "by topology relation").labels(kind=kind, **lab)
            for kind in ("resharded", "same_topology")}
        self._m_straggler = reg.counter(
            "dl4j_train_fleet_straggler_flags",
            "straggler flags raised (worker slower than the wave "
            "median by the configured factor)").labels(**lab)
        self._m_wave_s = reg.histogram(
            "dl4j_train_fleet_wave_seconds",
            "wave wall latency (dispatch to aggregate)").labels(**lab)
        ref = weakref.ref(self)
        for state in STATES:
            reg.gauge(
                "dl4j_train_fleet_workers",
                "supervised training workers by lifecycle state").labels(
                    state=state, **lab).set_function(
                (lambda st: lambda: (
                    (lambda o: o.state_counts().get(st, 0) if o else 0)(
                        ref())))(state))
        # crash-safe control plane (docs/OBSERVABILITY.md) — series
        # definitions shared with the fleet (statefile module)
        from deeplearning4j_tpu.utils.statefile import \
            controlplane_metrics

        self._m_restarts, self._m_adoptions = controlplane_metrics(
            "supervisor", self.run_label,
            lambda: (lambda o: o.incarnation if o else 0)(ref()),
            ("adopted", "dead", "recycled", "stray", "killed_stale"))

    # ------------------------------------------------------ membership
    def state_counts(self) -> Dict[str, int]:
        with self._sup_lock:
            counts = {s: 0 for s in STATES}
            for rec in self.members.values():
                counts[rec.state] += 1
            return counts

    def live_workers(self) -> List[SupervisedWorker]:
        with self._sup_lock:
            return [r for r in self.members.values()
                    if r.state in (STARTING, RUNNING, SUSPECT)]

    def _worker_id(self, slot: int, generation: int) -> str:
        base = (f"w{slot}" if generation == 0
                else f"w{slot}r{generation}")
        # incarnation-scoped ids for FRESH spawns of a restarted
        # control plane: a prior incarnation's survivor keeps its old
        # id (it re-announces it), so new spawns must never collide
        # with a rejoiner wearing the same slot number
        return base if self.incarnation == 0 \
            else f"{base}_i{self.incarnation}"

    def spawn_workers(self, n: Optional[int] = None) -> None:
        """Spawn the initial pool (idempotent; run() calls it). A
        restarted incarnation first replaces journaled slots whose
        processes did not survive (same slot, bumped generation — not
        charged to the respawn budget: this is the incarnation's
        initial pool), then fills any remainder with fresh slots."""
        n = self.n_workers if n is None else n
        while self._adopt_respawn and len(self.live_workers()) < n:
            slot, gen = self._adopt_respawn.pop(0)
            self._spawn_slot(slot, gen)
        with self._sup_lock:
            self._adopt_respawn.clear()
            have = len(self.live_workers())
        for _ in range(max(0, n - have)):
            slot = next(self._slot_seq)
            self._spawn_slot(slot, generation=0)

    def _spawn_slot(self, slot: int, generation: int) -> SupervisedWorker:
        wid = self._worker_id(slot, generation)
        proc = self.spawner.spawn(wid)
        rec = SupervisedWorker(wid, slot, proc=proc,
                               generation=generation)
        with self._sup_lock:
            self.members[wid] = rec
        log.info("supervisor %s: spawned worker %s (pid %d)",
                 self.run_label, wid, proc.pid)
        self._journal_write()
        return rec

    # ---------------------------------------- crash-safe control plane
    def _journal_write(self) -> None:
        """Commit the membership journal (utils/statefile.py atomic
        rename). Called at every transition: spawn, adopt, evict,
        close. A failed write is logged and survived — the previous
        committed journal stays valid, which at worst costs a restart
        one ladder rung (it adopts a slightly older membership and the
        pid fingerprints reject anything that changed)."""
        if self.journal is None:
            return
        with self._sup_lock:
            workers = {}
            for wid, rec in self.members.items():
                if rec.state in (EVICTED, DEAD) or rec.proc is None:
                    continue
                workers[wid] = {
                    "slot": rec.slot, "generation": rec.generation,
                    "pid": rec.proc.pid,
                    "start_time": rec.start_time,
                    "state": rec.state,
                    "performed": rec.performed,
                    "last_seq": rec.last_seq,
                }
            state = {
                "plane": "supervisor",
                "run": self.run_label,
                "incarnation": self.incarnation,
                "progress_port": self._progress.port,
                "n_workers": self.n_workers,
                "respawns_used": self.respawns_used,
                "checkpoint_dir": self.checkpoint_dir,
                "workers": workers,
                "written_at": time.time(),
            }
        with self._journal_io_lock:
            self.journal.try_write(state)

    def _adopt_prior(self, prior: dict) -> bool:
        """Re-adopt the previous incarnation's live children. Every
        journaled entry is fingerprint-verified (pid + start time):
        survivors become AdoptedProc members awaiting their reconnect
        re-announcement; dead or recycled pids are replaced by fresh
        spawns of the same slot (bumped generation). Returns True when
        at least one child was adopted."""
        self._m_restarts.inc()
        adopted = False
        max_slot = -1
        with self._sup_lock:
            for wid, w in (prior.get("workers") or {}).items():
                slot = int(w.get("slot", 0))
                gen = int(w.get("generation", 0))
                max_slot = max(max_slot, slot)
                pid = w.get("pid")
                kind = procs.classify_pid(pid, w.get("start_time"))
                if kind == "adopted":
                    proc = procs.AdoptedProc(pid, w.get("start_time"))
                    procs.register_spawned(proc)
                    rec = SupervisedWorker(wid, slot, proc=proc,
                                           generation=gen, adopted=True)
                    rec.performed = int(w.get("performed") or 0)
                    self.members[wid] = rec
                    adopted = True
                else:
                    # "recycled" = alive-but-mismatched start time (a
                    # stranger wearing the number: never touched, only
                    # replaced); "dead" is simply replaced
                    self._adopt_respawn.append((slot, gen + 1))
                self._m_adoptions[kind].inc()
                self.adoption_events.append(
                    {"worker": wid, "kind": kind, "pid": pid,
                     "slot": slot, "at": time.time()})
                log.warning("supervisor %s: incarnation %d %s prior "
                            "worker %s (pid %s)", self.run_label,
                            self.incarnation,
                            "re-adopts" if kind == "adopted"
                            else f"found {kind}", wid, pid)
            self.respawns_used = int(prior.get("respawns_used")
                                     or self.respawns_used)
            # fresh slots must never collide with journaled ones
            self._slot_seq = itertools.count(max_slot + 1)
        return adopted

    def _maybe_adopt_stray(self, wid: str, data: dict) -> None:
        """A progress hello from a worker this incarnation does not
        know — a survivor the (torn or stale) journal failed to name.
        Policy: adopted when its (pid, start_time) self-announcement
        verifies AND the pool has room; otherwise killed. Never
        ignored: an unknown live worker would keep taking tracker jobs
        while nobody owns its liveness — the leak this module exists
        to close."""
        if self.journal is None or not self._adoption_done:
            return  # non-journaled supervisors keep the old semantics;
            # mid-init hellos retry on the reporter's next beat
        pid = data.get("pid")
        start_time = data.get("start_time")
        if not pid:
            return  # a legacy hello carries no fingerprint: ignore
        if not procs.pid_matches(int(pid), start_time):
            return  # claimed fingerprint does not verify: not ours
        with self._sup_lock:
            if wid in self.members:
                return
            room = len(self.live_workers()) < self.n_workers
            if room:
                proc = procs.AdoptedProc(int(pid), start_time)
                procs.register_spawned(proc)
                slot = next(self._slot_seq)
                rec = SupervisedWorker(wid, slot, proc=proc,
                                       adopted=True)
                rec.performed = int(data.get("performed") or 0)
                self.members[wid] = rec
                self._m_adoptions["stray"].inc()
                self.adoption_events.append(
                    {"worker": wid, "kind": "stray", "pid": pid,
                     "slot": slot, "at": time.time()})
        if room:
            log.warning("supervisor %s: adopted stray rejoiner %s "
                        "(pid %s)", self.run_label, wid, pid)
            self._journal_write()
            return
        # over capacity: adopted-or-killed, never leaked — and never
        # double-adopted (the members check above is under the lock)
        log.warning("supervisor %s: killing stray rejoiner %s (pid %s)"
                    " — pool already whole", self.run_label, wid, pid)
        self._m_adoptions["killed_stale"].inc()
        self.adoption_events.append(
            {"worker": wid, "kind": "killed_stale", "pid": pid,
             "at": time.time()})
        self._progress.drop(wid)
        self.tracker.remove_worker(wid)
        try:
            procs.stop_process_group(
                procs.AdoptedProc(int(pid), start_time),
                term_first=False)
        except Exception:
            log.exception("killing stray worker %s failed", wid)

    # -------------------------------------------------- progress plane
    def _rec(self, wid: str) -> Optional[SupervisedWorker]:
        with self._sup_lock:
            return self.members.get(wid)

    def _on_worker_alive(self, wid: str) -> None:
        rec = self._rec(wid)
        if rec is None or rec.state in (EVICTED, DEAD):
            return  # never heartbeat an evicted member back in
        self.tracker.heartbeat(wid)
        if rec.state == STARTING:
            with self._sup_lock:
                rec.state = RUNNING
                rec.connected = True

    def _on_worker_progress(self, wid: str, data: dict) -> None:
        rec = self._rec(wid)
        if rec is None:
            # an unknown rejoiner from a previous incarnation:
            # adopt-or-kill (never leak, never double-adopt)
            self._maybe_adopt_stray(wid, data)
            return
        if rec.state in (EVICTED, DEAD):
            return
        now = time.monotonic()
        with self._sup_lock:
            if data.get("last_seq") is not None:
                rec.last_seq = int(data["last_seq"])
            advanced = False
            performed = int(data.get("performed", rec.performed))
            if performed > rec.performed:
                rec.performed = performed
                rec.last_step = performed
                rec.last_progress_t = now
                rec.job_seen_t = None  # its dispatch completed
                advanced = True
            job_s = data.get("job_s")
            if job_s is not None and advanced:
                if rec.performed == 1:
                    # a member's FIRST job carries its cold jit compile
                    # — counting it would straggler-flag every freshly
                    # (re)spawned worker
                    return
                rec.job_seconds.append(float(job_s))

    def _on_worker_gone(self, wid: str) -> None:
        rec = self._rec(wid)
        if rec is None:
            return
        with self._sup_lock:
            rec.connected = False
        # no explicit eviction here: heartbeats simply stop, and the
        # staleness sweep (the scaleout eviction contract) names it

    # ------------------------------------------------------ the monitor
    def _tick(self) -> None:
        """One supervision pass, run inside the master poll loop."""
        if self._aborted:
            raise SupervisorAbort(self._aborted)
        now = time.monotonic()
        self._watch_waves(now)
        self._watch_processes(now)
        self._watch_progress(now)
        self._watch_stale()
        self._drain_respawn_queue(now)
        if self._capacity_lost_pending:
            self._capacity_lost_pending = False
            self._elastic_resume()
        self._maybe_abort()

    def _watch_waves(self, now: float) -> None:
        """Wave-close bookkeeping: latency histogram, autosave cadence,
        straggler verdicts (judged at wave boundaries, where every
        member just reported a comparable unit of work)."""
        if self.waves == self._last_waves_seen:
            return
        closed = self.waves - self._last_waves_seen
        self._last_waves_seen = self.waves
        opened_at = getattr(self, "_wave_opened_at", None)
        if opened_at is not None:
            self._m_wave_s.observe(max(0.0, now - opened_at))
        self._check_stragglers()
        self._waves_since_save += closed
        if (self.saver is not None and self.save_every_waves_elastic
                and self._waves_since_save
                >= self.save_every_waves_elastic):
            self._waves_since_save = 0
            self._save_checkpoint()

    def _watch_processes(self, now: float) -> None:
        """A spawned process that died before (or after) connecting is
        evicted on the spot — no need to wait out the heartbeat window
        when the exit status already names the death. A process that is
        ALIVE but never opened its progress socket within
        `startup_grace` (hung mid-boot: it holds no job, sends no
        heartbeat, and would pin `_expecting_capacity` — and with it
        the wave barrier — forever) is evicted on the same grace the
        watermark gives a first job."""
        with self._sup_lock:
            recs = [r for r in self.members.values()
                    if r.state in (STARTING, RUNNING, SUSPECT)
                    and r.proc is not None]
        for rec in recs:
            if rec.proc.poll() is not None:
                reason = ("spawn_failed" if rec.state == STARTING
                          else "stale")
                self._evict(rec, reason,
                            detail=f"process exited "
                                   f"rc={rec.proc.returncode}")
            elif (rec.state == STARTING
                  and now - rec.spawned_at >= self.startup_grace):
                self._evict(rec, "spawn_failed",
                            detail=f"never connected within "
                                   f"{self.startup_grace:.0f}s")

    def _watch_progress(self, now: float) -> None:
        """The progress watermark: a worker HOLDING a dispatched job
        whose performed-count has not advanced within the window is
        hung — heartbeats (TCP-held or otherwise) notwithstanding."""
        assigned = {j.worker_id for j in self.tracker.jobs()}
        with self._sup_lock:
            recs = [r for r in self.members.values()
                    if r.state in (RUNNING, SUSPECT, STARTING)]
        for rec in recs:
            if rec.id in assigned:
                if rec.job_seen_t is None:
                    rec.job_seen_t = now
                    continue
                window = (self.progress_timeout if rec.performed > 0
                          else max(self.progress_timeout,
                                   self.startup_grace))
                stalled = now - max(rec.job_seen_t, rec.last_progress_t)
                if stalled >= window:
                    self._evict(
                        rec, "hung",
                        detail=f"no step progress for "
                               f"{stalled:.1f}s with a dispatched job "
                               f"(window {window:.1f}s)")
            else:
                rec.job_seen_t = None

    def _watch_stale(self) -> None:
        """Staleness sweep twin of runtime._evict_stale, but the
        supervisor ALSO owns the process: kill the group, requeue the
        orphan, schedule the respawn. (The base _evict_stale that runs
        after us finds nothing left to do.)"""
        for wid in self.tracker.stale_workers():
            rec = self._rec(wid)
            if rec is not None and rec.state not in (EVICTED, DEAD):
                self._evict(rec, "stale", detail="heartbeat timeout")

    def _check_stragglers(self) -> None:
        with self._sup_lock:
            live = [r for r in self.members.values()
                    if r.state in (RUNNING, SUSPECT)]
            means = [(r, r.mean_job_s()) for r in live]
            means = [(r, m) for r, m in means
                     if m is not None
                     and len(r.job_seconds) >= self.straggler_min_samples]
            if len(means) < 2:
                return
            flagged = []
            for rec, mean in means:
                # median of the OTHER members: with a small pool a
                # straggler drags a whole-pool median up with it and
                # could never exceed factor x its own contribution
                med = float(np.median([m for r, m in means
                                       if r is not rec]))
                if med <= 0:
                    continue
                if mean > self.straggler_factor * med:
                    rec.straggler_strikes += 1
                    if rec.state == RUNNING:
                        rec.state = SUSPECT
                    self._m_straggler.inc()
                    log.warning(
                        "supervisor %s: worker %s flagged straggler "
                        "(%.3fs/job vs wave median %.3fs, strike %d/%d)",
                        self.run_label, rec.id, mean, med,
                        rec.straggler_strikes, self.straggler_strikes)
                    if rec.straggler_strikes >= self.straggler_strikes:
                        flagged.append((rec, mean, med))
                else:
                    rec.straggler_strikes = 0
                    if rec.state == SUSPECT:
                        rec.state = RUNNING
        for rec, mean, med in flagged:
            self._evict(rec, "straggler",
                        detail=f"{mean:.3f}s/job vs median {med:.3f}s "
                               f"x{self.straggler_factor:g}")

    # -------------------------------------------------------- eviction
    def _evict(self, rec: SupervisedWorker, reason: str,
               detail: str = "") -> None:
        with self._sup_lock:
            if rec.state in (EVICTED, DEAD):
                return
            rec.state = EVICTED
            rec.evicted_at = time.monotonic()
            rec.eviction_reason = f"{reason}: {detail}" if detail \
                else reason
        log.warning("supervisor %s: evicting worker %s (%s)",
                    self.run_label, rec.id, rec.eviction_reason)
        self._m_evictions[reason].inc()
        # sever its telemetry plane FIRST: a SIGSTOP'd worker's kernel-
        # held socket must not heartbeat it back into the tracker
        self._progress.drop(rec.id)
        # reclaim the process BEFORE deciding the orphan's fate
        # (SIGKILL: a hung/stopped member will not honor SIGTERM). A
        # LIVE worker evicted between its add_update and clear_job RPCs
        # would otherwise race the check below — once the process is
        # dead and reaped, no further update can land.
        if rec.proc is not None:
            try:
                WorkerSpawner.stop(rec.proc, term_first=False)
            except Exception:
                log.exception("killing evicted worker %s failed", rec.id)
        # the scaleout eviction contract: remove + requeue the orphan —
        # UNLESS the worker already delivered its update (it died
        # between add_update and clear_job): the update will fold, so
        # redoing the job would train the same batch twice
        orphan = self.tracker.remove_worker(rec.id)
        if (orphan is not None and orphan.result is None
                and rec.id not in self.tracker.worker_updates()):
            from deeplearning4j_tpu.scaleout.api import Job

            self._orphan_jobs.append(Job(work=orphan.work,
                                         worker_id=orphan.worker_id,
                                         retries=orphan.retries,
                                         seq=orphan.seq))
        self._schedule_respawn(rec)
        self._journal_write()

    def _schedule_respawn(self, rec: SupervisedWorker) -> None:
        with self._sup_lock:
            if self.respawns_used >= self.max_respawns:
                rec.state = DEAD
                log.error(
                    "supervisor %s: respawn budget exhausted (%d/%d) — "
                    "capacity durably lost at slot %d",
                    self.run_label, self.respawns_used,
                    self.max_respawns, rec.slot)
                self._capacity_lost_pending = True
                return
            self.respawns_used += 1
            gen = rec.generation + 1
            backoff = self.respawn_backoff_s * (2 ** (gen - 1))
            self._respawn_queue.append({
                "slot": rec.slot, "generation": gen,
                "not_before": time.monotonic() + min(backoff, 30.0)})

    def _drain_respawn_queue(self, now: float) -> None:
        with self._sup_lock:
            due = [e for e in self._respawn_queue
                   if e["not_before"] <= now]
            self._respawn_queue = [e for e in self._respawn_queue
                                   if e["not_before"] > now]
        for entry in due:
            try:
                self._spawn_slot(entry["slot"], entry["generation"])
                self._m_respawns.inc()
            except Exception:
                log.exception("supervisor %s: respawn of slot %d failed",
                              self.run_label, entry["slot"])
                # count the failed attempt against the budget and retry
                # with doubled backoff (or declare capacity lost)
                fake = SupervisedWorker(
                    self._worker_id(entry["slot"], entry["generation"]),
                    entry["slot"], proc=None,
                    generation=entry["generation"])
                fake.state = EVICTED
                self._schedule_respawn(fake)

    def _expecting_capacity(self) -> bool:
        """Replacements in flight: queued respawns, or spawned members
        that have not yet connected (STARTING). While true, an open
        wave's barrier waits for the respawned member instead of
        closing early on the survivors."""
        with self._sup_lock:
            if self._respawn_queue:
                return True
            return any(r.state == STARTING
                       for r in self.members.values())

    def _maybe_abort(self) -> None:
        with self._sup_lock:
            live = len(self.live_workers())
            pending = len(self._respawn_queue)
        if live == 0 and pending == 0 and not self._capacity_lost_pending:
            self._aborted = (
                "no live workers and no respawn capacity left "
                f"(respawns used {self.respawns_used}/"
                f"{self.max_respawns})")
            raise SupervisorAbort(self._aborted)

    # ------------------------------------------------------ checkpoints
    @staticmethod
    def shard_params(params: np.ndarray, n_shards: int):
        """Split the packed params into one shard per worker — the
        checkpoint carries the run's topology in its shard table, and a
        restore onto fewer survivors is a true resharded reassembly
        (checkpoint/format.py coverage-checked stitch), not a file copy."""
        from deeplearning4j_tpu.checkpoint import format as ckfmt

        vec = np.asarray(params)
        n = max(1, int(n_shards))
        if vec.ndim != 1 or n == 1 or vec.size < n:
            return vec
        bounds = np.linspace(0, vec.size, n + 1, dtype=np.int64)
        shards = [
            ckfmt.HostShard(((int(lo), int(hi)),), vec[lo:hi].copy())
            for lo, hi in zip(bounds[:-1], bounds[1:])]
        return ckfmt.HostLeaf(dtype=ckfmt._dtype_name(vec.dtype),
                              shape=(int(vec.size),), shards=shards)

    def _exact_cursor(self) -> int:
        """The stream position a resume may safely seek to: the length
        of the CONTIGUOUS folded prefix (plus finally-dropped jobs),
        capped by the base cursor. A wave that closed around a
        carried-over orphan folds seqs out of order; counting folds
        alone would then label work as trained that never was —
        undershooting merely re-trains a batch (averaging tolerates
        it), overshooting silently loses one."""
        folded = set(self.folded_seqs)
        k = 0
        while k in folded:
            k += 1
        dropped = int(self.tracker.count(JOBS_DROPPED))
        return int(min(self._resume_cursor(), k + dropped))

    def _save_checkpoint(self, wait: bool = False) -> Optional[str]:
        if self.saver is None:
            return None
        current = self.tracker.get_current()
        if current is None:
            return None
        cursor = self._exact_cursor()
        if cursor == self._last_saved_step:
            # never re-save an already-committed step: rewriting tears
            # the existing committed dir open for the write window
            return None
        self._last_saved_step = cursor
        payload = {
            "format_version": 3,
            "conf_json": self.conf_json,
            "params": self.shard_params(np.asarray(current),
                                        len(self.live_workers())),
            "updater_state": None,
            "iteration_count": self.waves,
            "iterator_position": cursor,
            "metadata": {"waves": self.waves,
                         "n_workers": len(self.live_workers()),
                         "run": self.run_label},
            "saved_at": time.time(),
        }
        mesh_spec = {"axes": {"workers": len(self.live_workers())},
                     "strategy": "iterative_reduce"}
        return self.saver.save(payload, step=cursor,
                               mesh_spec=mesh_spec, wait=wait)

    def _apply_initial_resume(self, request: str) -> None:
        """`resume="auto"` (or an explicit checkpoint path): seed the
        run from the newest COMMITTED step before any worker trains."""
        from deeplearning4j_tpu.checkpoint.restore import discover_latest

        path = (self.checkpoint_dir if request == "auto" else request)
        if path is None:
            raise ValueError(
                "resume='auto' needs checkpoint_dir to discover from")
        try:
            root, step = discover_latest(path)
        except FileNotFoundError:
            return  # nothing saved yet: a fresh run
        except Exception as e:
            if request == "auto" and "no sharded checkpoint steps" in str(e):
                return  # fresh dir: auto-resume means "resume if any"
            raise
        self._restore_from(root, step, initial=True)

    def _restore_from(self, root: str, step: int,
                      initial: bool = False) -> dict:
        from deeplearning4j_tpu.checkpoint.restore import \
            load_payload_tree

        payload, manifest = load_payload_tree(root, step)
        params = payload.get("params")
        if params is not None and not isinstance(params, np.ndarray):
            # a tree checkpoint (e.g. written by a trainer): pack it in
            # the canonical sorted-key ravel order convert.py documents
            from jax.flatten_util import ravel_pytree

            params = np.asarray(ravel_pytree(params)[0])
        cursor = int(payload.get("iterator_position") or 0)
        src_workers = ((manifest.get("mesh") or {}).get("axes") or {}) \
            .get("workers")
        survivors = max(1, len(self.live_workers())) if not initial \
            else self.n_workers
        resharded = (src_workers is not None
                     and int(src_workers) != survivors)
        self.tracker.set_current(np.asarray(params))
        self.job_iterator.seek(cursor)
        # re-baseline the stream accounting at the checkpoint cursor:
        # everything before it is IN the restored params, everything
        # after it will be re-dispatched exactly once
        self.jobs_consumed = cursor
        self.jobs_aggregated = cursor
        dropped = self.tracker.count(JOBS_DROPPED)
        if dropped:
            self.tracker.increment(JOBS_DROPPED, -dropped)
        # re-baseline the audit trail: the restored params embody the
        # stream prefix [0, cursor) — including any dropped-job gaps
        # the checkpoint's cursor accounted for. Keeping a gap here
        # would stall _exact_cursor below the restore point forever
        # (every later save would re-hit the same step).
        self.folded_seqs = list(range(cursor))
        self._seq_of.clear()
        event = {"step": step, "cursor": cursor,
                 "source_workers": src_workers,
                 "survivors": survivors,
                 "resharded": resharded, "initial": initial,
                 "at": time.time()}
        self.resume_events.append(event)
        self._m_resumes["resharded" if resharded
                        else "same_topology"].inc()
        log.warning("supervisor %s: %s from checkpoint step %d "
                    "(cursor %d, %s -> %d workers)", self.run_label,
                    "seeded" if initial else "elastic resume",
                    step, cursor, src_workers, survivors)
        return event

    # --------------------------------------------------- elastic resume
    def _elastic_resume(self) -> None:
        """Capacity durably lost: restart the wave from the last
        COMMITTED checkpoint on the surviving topology. Ladder position
        two of three (respawn -> reshard-resume -> abort)."""
        survivors = self.live_workers()
        if not survivors:
            return  # abort path handles zero capacity
        t0 = time.monotonic()
        if self.saver is not None:
            # make any in-flight save durable BEFORE asking what the
            # newest committed step is
            try:
                self.saver.flush(timeout=60.0)
            except Exception:
                log.exception("flush before elastic resume failed")
        if self.saver is None or self.saver.latest_step() is None:
            # no checkpoint to roll back to: shrink the pool in place —
            # un-aggregated work is already requeued as orphans, so the
            # run continues smaller with nothing lost
            self.n_workers = len(survivors)
            log.warning(
                "supervisor %s: capacity lost with no committed "
                "checkpoint; continuing on %d survivor(s)",
                self.run_label, self.n_workers)
            return
        step = self.saver.latest_step()
        # drain survivors' in-flight jobs: a cleared-but-still-running
        # job would later report an update for work the rollback is
        # about to re-dispatch — wait for those updates, then discard
        # the whole pending set atomically
        live_ids = {r.id for r in survivors}
        drain_by = time.monotonic() + max(10.0, self.progress_timeout)
        while (any(j.worker_id in live_ids for j in self.tracker.jobs())
               and time.monotonic() < drain_by):
            time.sleep(self.interval)
        for job in self.tracker.jobs():
            self.tracker.clear_job(job.worker_id)
        self.tracker.clear_updates()
        self._orphan_jobs.clear()
        self._wave_size = 0
        event = self._restore_from(self.checkpoint_dir, step)
        self.n_workers = len(survivors)
        event["recovery_s"] = round(time.monotonic() - t0, 4)

    # ------------------------------------------------------ run surface
    def run(self, timeout: float = 300.0) -> np.ndarray:
        self.spawn_workers()
        ok = False
        try:
            final = super().run(timeout=timeout)
            if self.saver is not None and final is not None:
                self._save_checkpoint(wait=True)
            ok = True
            return final
        finally:
            # a failing run with a journal HANDS ITS CHILDREN OFF to
            # the next incarnation (the watchdog restarts us); a clean
            # finish tears everything down and clears the journal
            self.close(handoff=not ok)

    def close(self, handoff: bool = False) -> None:
        """Stop worker processes, the progress plane, and the saver.
        Safe to call repeatedly (run() calls it on every exit path).

        `handoff=True` (only meaningful with a journal): the control
        plane is dying but the RUN is not — leave the warm worker
        processes alive for the next incarnation to re-adopt. The
        journal gets a final commit naming them, they are released
        from THIS incarnation's atexit orphan sweep
        (procs.release_spawned — the sweep is scoped to what the
        current incarnation still owns), and the tracker is NOT
        finished, so workers enter their bounded reconnect loop
        instead of exiting."""
        if handoff and self.journal is not None:
            with self._sup_lock:
                self._respawn_queue.clear()
                recs = [r for r in self.members.values()
                        if r.proc is not None
                        and r.state not in (EVICTED, DEAD)]
            self._journal_write()
            for rec in recs:
                procs.release_spawned(rec.proc)
            log.warning(
                "supervisor %s: handing %d live worker(s) off to the "
                "next incarnation (journal %s)", self.run_label,
                len(recs), self.journal.path)
            self._progress.close()
            if self.saver is not None:
                try:
                    self.saver.close(timeout=60.0)
                except Exception:
                    log.exception("closing checkpoint writer failed")
                self.saver = None
            return
        self.tracker.finish()  # workers exit their loops
        with self._sup_lock:
            recs = [r for r in self.members.values()
                    if r.proc is not None]
            self._respawn_queue.clear()
        for rec in recs:
            try:
                WorkerSpawner.stop(rec.proc, timeout=5.0)
            except Exception:
                log.exception("stopping worker %s failed", rec.id)
        self._progress.close()
        if self.saver is not None:
            try:
                self.saver.close(timeout=60.0)
            except Exception:
                log.exception("closing checkpoint writer failed")
            self.saver = None
        if self.journal is not None:
            # nothing is handed off: a stale journal must not trick
            # the next incarnation into adopting recycled pids (the
            # fingerprints would reject them, but why leave the trap)
            self.journal.clear()

    # --------------------------------------------------- observability
    def _status_extra(self) -> Dict[str, Any]:
        with self._sup_lock:
            workers = {wid: rec.snapshot()
                       for wid, rec in self.members.items()}
        return {
            "workers": workers,
            "states": self.state_counts(),
            "respawns_used": self.respawns_used,
            "max_respawns": self.max_respawns,
            "min_workers": self.min_workers,
            "resumes": list(self.resume_events),
            "folded_jobs": len(self.folded_seqs),
            "checkpoint_dir": self.checkpoint_dir,
            "incarnation": self.incarnation,
            "state_dir": self.state_dir,
            "adoptions": list(self.adoption_events),
        }

    def _health(self) -> Dict[str, Any]:
        """Quorum verdict for /healthz: 503 once fewer than
        `min_workers` members are live — the signal a cluster manager
        watches to replace the whole run."""
        live = len(self.live_workers())
        return {"ok": live >= self.min_workers,
                "live_workers": live,
                "min_workers": self.min_workers,
                "respawns_used": self.respawns_used,
                "incarnation": self.incarnation}
