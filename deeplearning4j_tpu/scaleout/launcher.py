"""Multi-process launcher: master / worker roles in separate processes.

Parity: reference `DeepLearning4jDistributedApp` (akka …/actor/runner/ —
main() with role "master" or "worker"), `DeepLearning4jDistributed.setup`
(master boots router/tracker/actors, :239; worker connects and heartbeats,
:322-345), with ZooKeeper supplying the startup Configuration
(ZooKeeperConfigurationRegister.java:100) and the performer class wired by
name through the config (WorkerPerformerFactory.WORKER_PERFORMER key).

TPU-native design: the master process owns the InMemoryStateTracker and
serves it over `rpc.StateTrackerServer`; its run configuration (tracker
endpoint + performer class + performer conf) is published through
`registry.ConfigRegistry` on a shared filesystem. Worker processes
resolve the run by name, connect a `RemoteStateTracker`, build their
performer reflectively (restricted to this package) and run the same
worker loop the in-process runtime uses. Device-level collectives are
orthogonal: on a real multi-host pod each worker process additionally
calls `jax.distributed.initialize` (--jax-coordinator/--num-processes/
--process-id) so in-worker training can shard over the pod's global
device mesh while THIS layer stays pure control plane.
"""

from __future__ import annotations

import argparse
import importlib
import logging
import time
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.scaleout.registry import ConfigRegistry
from deeplearning4j_tpu.scaleout.rpc import (RemoteStateTracker,
                                             StateTrackerServer)
from deeplearning4j_tpu.scaleout.runtime import DistributedRuntime, _Worker
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker

log = logging.getLogger(__name__)

#: config keys (reference WorkerPerformerFactory.WORKER_PERFORMER et al.)
PERFORMER_CLASS = "performer_class"
PERFORMER_CONF = "performer_conf"
TRACKER_ADDRESS = "tracker_address"
WORK_DIR = "work_dir"  # shared WorkRetriever directory (optional)


def _resolve_performer(class_path: str):
    """Import a performer class by dotted name, restricted to this package
    (the config file is data, not code — don't let it import arbitrary
    modules)."""
    if not class_path.startswith("deeplearning4j_tpu."):
        raise ValueError(
            f"performer_class must live under deeplearning4j_tpu.*, "
            f"got {class_path!r}")
    module_name, _, cls_name = class_path.rpartition(".")
    module = importlib.import_module(module_name)
    return getattr(module, cls_name)


class MultiProcessMaster(DistributedRuntime):
    """DistributedRuntime whose workers live in OTHER processes: serves the
    tracker over TCP, publishes the run config, and runs the same
    dispatch/aggregate loop against remotely-registered workers."""

    def __init__(self, job_iterator, *, run_name: str,
                 registry: ConfigRegistry,
                 performer_class: str,
                 performer_conf: Optional[Dict[str, Any]] = None,
                 n_workers: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 conf_json: Optional[str] = None,
                 work_dir: Optional[str] = None,
                 status_port: Optional[int] = None,
                 status_extra=None, status_health=None,
                 **kw):
        if work_dir is not None:
            from deeplearning4j_tpu.scaleout.api import LocalWorkRetriever
            kw.setdefault("work_retriever", LocalWorkRetriever(work_dir))
        super().__init__(job_iterator, performer_factory=None,
                         n_workers=n_workers, **kw)
        self.conf_json = conf_json
        self.run_name = run_name
        self.registry = registry
        self.server = StateTrackerServer(self.tracker, host=host, port=port)
        self.server.start()
        # live status endpoint (reference: Dropwizard UI embedded in the
        # Hazelcast tracker, BaseHazelCastStateTracker.java:181-189).
        # status_port=0 picks an ephemeral port; None disables.
        self.status_server = None
        if status_port is not None:
            from deeplearning4j_tpu.scaleout.status import StatusServer
            self.status_server = StatusServer(
                self.tracker, runtime=self, host=host,
                port=status_port, extra=status_extra,
                health=status_health).start()
        run_conf = {
            TRACKER_ADDRESS: self.server.address,
            PERFORMER_CLASS: performer_class,
            PERFORMER_CONF: performer_conf or {},
            "n_workers": n_workers,
        }
        if self.status_server is not None:
            run_conf["status_address"] = self.status_server.address
        if work_dir is not None:
            run_conf[WORK_DIR] = work_dir
        registry.register_run(run_name, run_conf)

    def start_workers(self):  # workers are separate processes
        pass

    def run(self, timeout: float = 120.0) -> np.ndarray:
        try:
            return super().run(timeout=timeout)
        finally:
            self.server.stop()
            if self.status_server is not None:
                self.status_server.stop()
            self.registry.unregister_run(self.run_name)


def run_worker(*, registry_root: str, run_name: str, worker_id: str,
               heartbeat_interval: float = 0.01,
               registration_timeout: float = 30.0) -> int:
    """Worker-process entry: resolve the run, connect, work until the
    master finishes. Returns the number of jobs performed."""
    registry = ConfigRegistry(registry_root)
    conf = registry.retrieve_run(run_name, timeout=registration_timeout)
    tracker = RemoteStateTracker(conf[TRACKER_ADDRESS])
    performer_cls = _resolve_performer(conf[PERFORMER_CLASS])
    performer = performer_cls()
    if conf.get(PERFORMER_CONF):
        performer.setup(conf[PERFORMER_CONF])
    retriever = None
    if conf.get(WORK_DIR):
        from deeplearning4j_tpu.scaleout.api import LocalWorkRetriever
        retriever = LocalWorkRetriever(conf[WORK_DIR])
    worker = _Worker(worker_id, tracker, performer,
                     interval=heartbeat_interval,
                     work_retriever=retriever)
    log.info("worker %s joined run %s at %s", worker_id, run_name,
             conf[TRACKER_ADDRESS])
    try:
        worker.run()  # blocks until tracker.is_done()
    except ConnectionError as e:
        # master gone = shutdown signal for a remote worker. Server-side
        # tracker failures surface as RuntimeError and must NOT be
        # swallowed as a clean exit — let them propagate to a nonzero
        # process exit so the launcher/test harness sees the failure.
        log.info("worker %s: master connection lost (%s), exiting", worker_id,
                 e)
    finally:
        tracker.close()
    return worker.performed


def _maybe_init_jax_distributed(args) -> None:
    if args.jax_coordinator:
        from deeplearning4j_tpu.parallel import multihost
        multihost.initialize(args.jax_coordinator,
                             num_processes=args.num_processes,
                             process_id=args.process_id)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.scaleout.launcher",
        description="Launch a distributed-training worker process")
    p.add_argument("role", choices=["worker"],
                   help="master runs embedded in the driver program via "
                        "MultiProcessMaster; only workers launch from the "
                        "CLI")
    p.add_argument("--registry", required=True,
                   help="ConfigRegistry root directory (shared filesystem)")
    p.add_argument("--run", required=True, help="run name to join")
    p.add_argument("--worker-id", required=True)
    p.add_argument("--heartbeat-interval", type=float, default=0.01)
    p.add_argument("--registration-timeout", type=float, default=30.0,
                   help="seconds to wait for the run to appear in the "
                        "registry (raise for later-phase runs, e.g. the "
                        "train phase behind a distributed vocab build)")
    p.add_argument("--jax-coordinator", default=None,
                   help="host:port for jax.distributed.initialize "
                        "(multi-host pods)")
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=0)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    _maybe_init_jax_distributed(args)
    performed = run_worker(registry_root=args.registry, run_name=args.run,
                           worker_id=args.worker_id,
                           heartbeat_interval=args.heartbeat_interval,
                           registration_timeout=args.registration_timeout)
    log.info("worker %s done: %d jobs", args.worker_id, performed)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
