"""Artifact plane: URI-addressed blob storage for datasets/checkpoints.

Parity: reference deeplearning4j-aws S3 stack — `S3Downloader` /
`S3Uploader` (aws/s3/reader/, aws/s3/uploader/), `BucketIterator`
(iterate a bucket's objects), `BaseS3DataSetIterator` (DataSets streamed
from bucket objects), `DataSetLoader`; and the HDFS twins
(hadoop/util/HdfsUtils, BaseHdfsDataSetIterator).

TPU-native design: the artifact plane on a pod is GCS (SURVEY §5).
Remote schemes (`gs://`, `s3://`, `hdfs://`) resolve to local mount
roots (gcsfuse et al.) via the same mount table `UriModelSaver` uses —
after resolution everything is plain file IO with atomic-rename
publication, so the one code path is testable without cloud credentials
and identical on a real pod.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet, DataSetIterator
from deeplearning4j_tpu.scaleout.checkpoint import (UriModelSaver,
                                                    dump_payload,
                                                    load_payload)

__all__ = ["ArtifactStore", "StorageDataSetIterator"]


class ArtifactStore:
    """get/put/list over a URI root (reference S3Downloader/S3Uploader/
    BucketIterator rolled into one store object)."""

    def __init__(self, root_uri: str,
                 mounts: Optional[Dict[str, str]] = None):
        self.root_uri = root_uri
        mounts = dict(mounts or {})
        env_root = os.environ.get("DL4J_TPU_ARTIFACT_ROOT")
        if env_root:
            for scheme in UriModelSaver.REMOTE_SCHEMES:
                mounts.setdefault(scheme, env_root)
        self.root = UriModelSaver._resolve(root_uri, mounts)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.normpath(os.path.join(self.root, key))
        if not path.startswith(os.path.normpath(self.root) + os.sep) \
                and path != os.path.normpath(self.root):
            raise ValueError(f"key {key!r} escapes the store root")
        return path

    # ------------------------------------------------------------- blobs
    def put_bytes(self, key: str, data: bytes) -> str:
        """Atomic publish (reference S3Uploader.upload)."""
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path

    def get_bytes(self, key: str) -> bytes:
        """reference S3Downloader.download."""
        with open(self._path(key), "rb") as f:
            return f.read()

    def upload_file(self, local_path: str, key: Optional[str] = None) -> str:
        with open(local_path, "rb") as f:
            return self.put_bytes(key or os.path.basename(local_path),
                                  f.read())

    def download_file(self, key: str, local_path: str) -> str:
        data = self.get_bytes(key)
        parent = os.path.dirname(local_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(data)
        return local_path

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete(self, key: str) -> None:
        path = self._path(key)
        if os.path.exists(path):
            os.unlink(path)

    # ----------------------------------------------------------- listing
    def keys(self, prefix: str = "") -> List[str]:
        """Sorted object keys under a prefix (reference BucketIterator).
        Skips in-flight `.tmp` files — they are unpublished."""
        base = self._path(prefix) if prefix else self.root
        out: List[str] = []
        if not os.path.isdir(base):
            return out
        for dirpath, _dirs, files in os.walk(base):
            for name in files:
                if name.endswith(".tmp"):
                    continue
                full = os.path.join(dirpath, name)
                out.append(os.path.relpath(full, self.root))
        return sorted(out)

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    # ----------------------------------------------------------- datasets
    def put_dataset(self, key: str, ds: DataSet) -> str:
        """Publish a DataSet with the no-pickle npz+JSON codec
        (reference DataSetLoader/S3 dataset staging)."""
        return self.put_bytes(key, dump_payload(
            {"features": np.asarray(ds.features),
             "labels": np.asarray(ds.labels)}))

    def get_dataset(self, key: str) -> DataSet:
        tree = load_payload(self.get_bytes(key))
        return DataSet(np.asarray(tree["features"]),
                       np.asarray(tree["labels"]))


class StorageDataSetIterator(DataSetIterator):
    """Stream DataSets from a store prefix, one object per batch
    (reference BaseS3DataSetIterator / BaseHdfsDataSetIterator: iterate
    bucket objects, parse each into a DataSet)."""

    def __init__(self, store: ArtifactStore, prefix: str = ""):
        self.store = store
        self.prefix = prefix
        self._keys = store.keys(prefix)
        if not self._keys:
            raise ValueError(
                f"no datasets under prefix {prefix!r} in {store.root_uri}")
        first = store.get_dataset(self._keys[0])
        self._input_columns = int(first.features.shape[-1])
        self._total_outcomes = int(first.labels.shape[-1])
        super().__init__(batch_size=first.num_examples,
                         num_examples=len(self._keys))

    def input_columns(self) -> int:
        return self._input_columns

    def total_outcomes(self) -> int:
        return self._total_outcomes

    def has_next(self) -> bool:
        return self.cursor < len(self._keys)

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.has_next():
            raise StopIteration
        ds = self.store.get_dataset(self._keys[self.cursor])
        self.cursor += 1
        if self.pre_processor is not None:
            ds = self.pre_processor(ds)
        return ds
