"""Worker performers: the compute plugged into distributed workers.

Parity: reference NeuralNetWorkPerformer.java:32-66 /
BaseMultiLayerNetworkWorkPerformer.java:32-57 — deserialize the conf JSON,
build the net, fit on the job's DataSet, result = packed params;
`update()` = setParameters. Configs travel as JSON strings (the reference's
wire format, SURVEY §5 config system).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from deeplearning4j_tpu.scaleout.api import Job, WorkerPerformer


class NeuralNetWorkPerformer(WorkerPerformer):
    """Fit a MultiLayerNetwork on each job's DataSet; emit packed params."""

    CONF_JSON = "conf_json"  # config key (reference WORKER_PERFORMER wiring)

    def __init__(self, conf_json: str = None, epochs: int = 1):
        self.conf_json = conf_json
        self.epochs = epochs
        self._net = None

    def setup(self, conf: Dict[str, Any]) -> None:
        self.conf_json = conf[self.CONF_JSON]
        self.epochs = int(conf.get("epochs", 1))
        self._ensure_net()

    def _ensure_net(self):
        if self._net is None:
            if self.conf_json is None:
                raise ValueError("NeuralNetWorkPerformer needs conf_json")
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            self._net = MultiLayerNetwork.from_config_json(self.conf_json)
        return self._net

    @property
    def network(self):
        return self._ensure_net()

    def perform(self, job: Job) -> None:
        net = self._ensure_net()
        ds = job.work
        net.fit(np.asarray(ds.features), np.asarray(ds.labels),
                epochs=self.epochs)
        job.result = np.asarray(net.params())

    def update(self, *args: Any) -> None:
        """Install new global parameters (reference update() = setParams)."""
        net = self._ensure_net()
        net.set_parameters(np.asarray(args[0]))
