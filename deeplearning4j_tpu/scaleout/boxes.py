"""Box creation: bring cloud worker hosts into existence.

Parity: reference `Ec2BoxCreator`
(deeplearning4j-aws/.../aws/ec2/Ec2BoxCreator.java:35,127-134 —
`create()` calls runInstances with AMI/size/security-group and collects
instance ids; `blowupBoxes()` terminates them) feeding `ClusterSetup`
(ClusterSetup.java:40: create boxes, then provision each).

TPU-native design: the cloud API is driven through its own CLI (`gcloud`
for TPU VMs) rather than an embedded SDK — the command runner is
injectable so tests (and air-gapped environments) record commands
instead of executing them. `GceTpuBoxCreator.create()` returns the
worker hostnames; hand them to `ClusterSetup` as `SshTransport`s (or let
`cluster_hosts()` do it) and the existing provisioning layer takes over.
`LocalBoxCreator` is the embedded tier: n "boxes" on this host.
"""

from __future__ import annotations

import json
import logging
import subprocess
from typing import Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.scaleout.provision import (LocalTransport,
                                                   SshTransport, Transport)

log = logging.getLogger(__name__)

__all__ = ["BoxCreator", "LocalBoxCreator", "GceTpuBoxCreator",
           "cluster_hosts"]

#: runner signature: (argv) -> stdout. Injectable for tests/air-gapped use.
Runner = Callable[[Sequence[str]], str]


def _subprocess_runner(argv: Sequence[str]) -> str:
    proc = subprocess.run(list(argv), capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{argv[0]} failed (rc {proc.returncode}): {proc.stderr.strip()}")
    return proc.stdout


class BoxCreator:
    """Create/destroy worker hosts (reference Ec2BoxCreator.create /
    blowupBoxes)."""

    def create(self) -> List[str]:
        """Bring the boxes up; returns host identifiers for transports."""
        raise NotImplementedError

    def blow_away(self) -> None:
        """Terminate everything create() made (reference blowupBoxes)."""
        raise NotImplementedError

    def transport_for(self, host: str) -> Transport:
        raise NotImplementedError


class LocalBoxCreator(BoxCreator):
    """n logical boxes on this host — the embedded/test tier (boxes are
    free; transports are LocalTransport)."""

    def __init__(self, n_boxes: int = 2):
        self.n_boxes = n_boxes

    def create(self) -> List[str]:
        return [f"local-{i}" for i in range(self.n_boxes)]

    def blow_away(self) -> None:
        pass

    def transport_for(self, host: str) -> Transport:
        return LocalTransport()


class GceTpuBoxCreator(BoxCreator):
    """TPU-VM boxes via the gcloud CLI (the Ec2BoxCreator equivalent for
    the platform this framework targets).

    `create()` issues `gcloud compute tpus tpu-vm create` per box and
    returns the worker hostnames reported by `describe` (multi-host pod
    slices report one endpoint per host — all of them are returned, so a
    v5e-16 slice yields 4 hosts for ClusterSetup). AMI/instance-type/
    security-group become accelerator-type/runtime-version/network.
    """

    def __init__(self, name_prefix: str, *, zone: str,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 n_slices: int = 1, project: Optional[str] = None,
                 network: Optional[str] = None,
                 ssh_user: Optional[str] = None,
                 runner: Runner = _subprocess_runner):
        self.name_prefix = name_prefix
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.n_slices = n_slices
        self.project = project
        self.network = network
        self.ssh_user = ssh_user
        self.runner = runner
        self.created: List[str] = []  # slice names

    def _base(self, verb: str, name: str) -> List[str]:
        argv = ["gcloud", "compute", "tpus", "tpu-vm", verb, name,
                "--zone", self.zone]
        if self.project:
            argv += ["--project", self.project]
        return argv

    def _slice_name(self, i: int) -> str:
        return f"{self.name_prefix}-{i}"

    def create(self) -> List[str]:
        hosts: List[str] = []
        for i in range(self.n_slices):
            name = self._slice_name(i)
            argv = self._base("create", name) + [
                "--accelerator-type", self.accelerator_type,
                "--version", self.runtime_version]
            if self.network:
                argv += ["--network", self.network]
            self.runner(argv)
            self.created.append(name)
            hosts.extend(self._hosts_of(name))
        log.info("created %d slice(s) -> %d worker host(s)",
                 self.n_slices, len(hosts))
        return hosts

    def _hosts_of(self, name: str) -> List[str]:
        out = self.runner(self._base("describe", name) + ["--format", "json"])
        desc: Dict = json.loads(out)
        endpoints = desc.get("networkEndpoints", [])
        hosts = [e.get("ipAddress") for e in endpoints if e.get("ipAddress")]
        if not hosts:
            raise RuntimeError(f"no network endpoints reported for {name}")
        return hosts

    def blow_away(self) -> None:
        # every slice gets its delete attempt (one failure must not leak
        # the rest — these are billed machines); already-gone slices are
        # treated as success, other failures stay in `created` so a
        # retry converges, and the combined error is raised at the end
        errors = []
        remaining = []
        for name in self.created:
            try:
                self.runner(self._base("delete", name) + ["--quiet"])
            except RuntimeError as e:
                if "not found" in str(e).lower():
                    continue  # deleted out-of-band: goal state reached
                errors.append(f"{name}: {e}")
                remaining.append(name)
        self.created = remaining
        if errors:
            raise RuntimeError("blow_away left slice(s) running: "
                               + "; ".join(errors))

    def transport_for(self, host: str) -> Transport:
        return SshTransport(host, user=self.ssh_user)


def cluster_hosts(creator: BoxCreator,
                  worker_prefix: str = "w") -> Dict[str, Transport]:
    """create() boxes and shape them as the `hosts` mapping ClusterSetup
    takes (worker-id -> Transport)."""
    return {f"{worker_prefix}{i}": creator.transport_for(h)
            for i, h in enumerate(creator.create())}
