"""Scaleout: the distributed-training contract and runtimes.

Parity: reference deeplearning4j-scaleout — the scaleout API
(…/scaleout/job/Job.java, perform/WorkerPerformer.java,
aggregator/JobAggregator.java, api/statetracker/StateTracker.java,
api/workrouter/WorkRouter.java), the Akka runtime (MasterActor/WorkerActor/
BatchActor heartbeat choreography), and the Spark/YARN iterative-reduce
variants — all of which implement data-parallel parameter averaging.

TPU-native design: the DATA plane (parameter exchange) belongs on the chips
— `parallel.DataParallelTrainer` (per-step psum over ICI) and
`parallel.ParameterAveragingTrainer` (epoch-wave pmean, behavioral parity
with MultiLayerNetwork.merge). The scaleout package is the HOST-side control
plane the reference built actors/Hazelcast for: job routing, worker registry,
heartbeats/eviction, update accumulation, counters, early-stop state, and
checkpointing — runnable fully in-process (the reference's
BaseTestDistributed / IRUnit tier) and designed so a multi-host deployment
swaps the in-memory tracker for one backed by jax.distributed's
coordination service.
"""

from deeplearning4j_tpu.scaleout.api import (  # noqa: F401
    CollectionJobIterator,
    DataSetJobIterator,
    HogWildWorkRouter,
    IterativeReduceWorkRouter,
    Job,
    JobAggregator,
    JobIterator,
    LocalFileUpdateSaver,
    LocalWorkRetriever,
    WorkRetriever,
    InMemoryUpdateSaver,
    WorkerPerformer,
    WorkRouter,
)
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker  # noqa: F401
from deeplearning4j_tpu.scaleout.aggregator import (  # noqa: F401
    ParameterAveragingAggregator,
)
from deeplearning4j_tpu.scaleout.perform import NeuralNetWorkPerformer  # noqa: F401
from deeplearning4j_tpu.scaleout.runtime import DistributedRuntime  # noqa: F401
from deeplearning4j_tpu.scaleout.checkpoint import (  # noqa: F401
    DefaultModelSaver,
    load_checkpoint,
)
from deeplearning4j_tpu.scaleout.checkpoint import UriModelSaver  # noqa: F401
from deeplearning4j_tpu.scaleout.registry import ConfigRegistry  # noqa: F401
from deeplearning4j_tpu.scaleout.supervisor import (  # noqa: F401
    SupervisorAbort,
    TrainingSupervisor,
    WorkerSpawner,
)
from deeplearning4j_tpu.scaleout.storage import (  # noqa: F401
    ArtifactStore,
    StorageDataSetIterator,
)
from deeplearning4j_tpu.scaleout.provision import (  # noqa: F401
    ClusterSetup,
    HostProvisioner,
    LocalTransport,
    SshTransport,
)
