"""StateTracker RPC: the cross-process control plane.

Parity: the reference's control plane is Hazelcast replicated data
structures reached over the network (BaseHazelCastStateTracker.java
master/worker/embedded connection modes :470-530) plus Akka remoting.
Here the master process owns ONE InMemoryStateTracker and serves it over
a tiny framed-TCP protocol; workers in other processes (or other hosts,
over DCN) talk to it through `RemoteStateTracker`, which duck-types the
tracker surface the worker loop uses.

This is deliberately a CONTROL plane: job descriptors, heartbeats,
counters and packed parameter vectors. On a TPU pod the heavy gradient
exchange rides ICI/DCN collectives inside each worker (parallel/), never
this socket.

Wire format: 8-byte big-endian length + the checkpoint codec's npz bytes
(scaleout/checkpoint.py dump_payload — arrays as raw npy members, JSON
manifest, nothing unpickled on receive), so a malicious peer can at worst
cause a ValueError, never code execution.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import struct
import threading
import uuid
from typing import Any, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.scaleout.api import Job
from deeplearning4j_tpu.scaleout.checkpoint import dump_payload, load_payload

log = logging.getLogger(__name__)

#: Tracker methods reachable over RPC (everything else is a protocol error).
ALLOWED_METHODS = frozenset({
    "add_worker", "remove_worker", "workers", "heartbeat", "heartbeats",
    "stale_workers", "add_job", "job_for", "clear_job", "jobs",
    "add_update", "worker_updates", "load_update", "clear_update",
    "clear_updates", "set_current", "get_current", "needs_replicate",
    "done_replicating", "increment", "count", "counters", "define", "get",
    "set_patience", "patience", "report_loss", "best_loss", "early_stop",
    "input_split", "batch_size", "finish", "is_done",
})


# ------------------------------------------------------------------ codec
def _to_wire(obj: Any) -> Any:
    """Jobs (and DataSet-bearing work) -> codec-friendly dicts."""
    if isinstance(obj, Job):
        wire = {"__job__": True,
                "work": _to_wire(obj.work),
                "result": _to_wire(obj.result),
                "worker_id": obj.worker_id,
                "retries": obj.retries}
        if obj.seq is not None:  # omit-when-absent keeps old frames valid
            wire["seq"] = int(obj.seq)
        return wire
    if hasattr(obj, "features") and hasattr(obj, "labels"):  # DataSet
        return {"__dataset__": True,
                "features": np.asarray(obj.features),
                "labels": np.asarray(obj.labels)}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_wire(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_wire(v) for k, v in obj.items()}
    return obj


def _from_wire(obj: Any) -> Any:
    if isinstance(obj, dict):
        if obj.get("__job__"):
            seq = obj.get("seq")
            return Job(work=_from_wire(obj["work"]),
                       worker_id=obj["worker_id"],
                       result=_from_wire(obj["result"]),
                       retries=int(obj["retries"]),
                       seq=None if seq is None else int(seq))
        if obj.get("__dataset__"):
            from deeplearning4j_tpu.datasets.api import DataSet
            return DataSet(obj["features"], obj["labels"])
        return {k: _from_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_wire(v) for v in obj)
    return obj


def _send_frame(sock: socket.socket, payload: dict) -> None:
    data = dump_payload(_to_wire(payload))
    sock.sendall(struct.pack(">Q", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf


MAX_FRAME = 1 << 31  # 2 GiB: larger than any packed parameter vector here


def _recv_frame(sock: socket.socket) -> dict:
    (length,) = struct.unpack(">Q", _recv_exact(sock, 8))
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return _from_wire(load_payload(_recv_exact(sock, length)))


# ----------------------------------------------------------------- server
class _TrackerHandler(socketserver.BaseRequestHandler):
    def setup(self):
        with self.server.active_lock:  # type: ignore[attr-defined]
            self.server.active_conns.add(self.request)  # type: ignore

    def finish(self):
        with self.server.active_lock:  # type: ignore[attr-defined]
            self.server.active_conns.discard(self.request)  # type: ignore

    def handle(self):
        tracker = self.server.tracker  # type: ignore[attr-defined]
        dedup = self.server.dedup  # type: ignore[attr-defined]
        dedup_lock = self.server.dedup_lock  # type: ignore[attr-defined]
        while True:
            try:
                req = _recv_frame(self.request)
            except (ConnectionError, OSError):
                return
            # At-most-once execution: a client that lost the connection
            # after the server executed its call re-sends the SAME
            # (client, seq); replay the cached response instead of
            # re-executing non-idempotent methods (increment, add_update).
            # Clients serialize calls, so one cached entry per client
            # suffices.
            client, seq = req.get("client"), req.get("seq")
            if client is not None:
                with dedup_lock:
                    cached = dedup.get(client)
                if cached is not None and cached[0] == seq:
                    try:
                        _send_frame(self.request, cached[1])
                        continue
                    except (ConnectionError, OSError):
                        return
            try:
                method = req.get("method")
                if method not in ALLOWED_METHODS:
                    raise ValueError(f"method not allowed: {method!r}")
                value = getattr(tracker, method)(*req.get("args", []))
                resp = {"ok": True, "value": value}
            except Exception as e:  # report, keep serving
                log.exception("tracker RPC %s failed", req.get("method"))
                resp = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
            if client is not None:
                with dedup_lock:
                    dedup[client] = (seq, resp)
            try:
                _send_frame(self.request, resp)
            except (ConnectionError, OSError):
                return


class StateTrackerServer:
    """Serve an InMemoryStateTracker over TCP (threaded, one thread per
    connected worker — workers hold one long-lived connection each)."""

    def __init__(self, tracker, host: str = "127.0.0.1", port: int = 0):
        self.tracker = tracker

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _TrackerHandler)
        self._server.tracker = tracker  # type: ignore[attr-defined]
        self._server.dedup = {}  # type: ignore[attr-defined]
        self._server.dedup_lock = threading.Lock()  # type: ignore[attr-defined]
        self._server.active_conns = set()  # type: ignore[attr-defined]
        self._server.active_lock = threading.Lock()  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tracker-server",
            daemon=True)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "StateTrackerServer":
        self._thread.start()
        log.info("StateTracker serving on %s", self.address)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # sever live worker connections too: a stopped master must look
        # to its workers exactly like a SIGKILLed one (kernel FIN), or
        # an in-process restart leaves them talking to a zombie tracker
        # through handler threads the shutdown never touches
        with self._server.active_lock:  # type: ignore[attr-defined]
            conns = list(self._server.active_conns)  # type: ignore
            self._server.active_conns.clear()  # type: ignore
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


# ----------------------------------------------------------------- client
class RemoteStateTracker:
    """Client-side StateTracker: same surface as InMemoryStateTracker,
    every call an RPC to the master's tracker server."""

    def __init__(self, address: str, timeout: float = 30.0,
                 retries: int = 3):
        host, port = address.rsplit(":", 1)
        self._addr: Tuple[str, int] = (host, int(port))
        self._timeout = timeout
        self._retries = retries
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self.heartbeat_timeout = None  # server decides staleness
        self._client_id = uuid.uuid4().hex  # at-most-once dedup identity
        self._seq = 0

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _call(self, method: str, *args: Any) -> Any:
        with self._lock:
            self._seq += 1  # same seq across retries of THIS call: the
            # server replays its cached response instead of re-executing
            last_err: Optional[Exception] = None
            for _ in range(self._retries):
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    _send_frame(self._sock, {"method": method,
                                             "args": list(args),
                                             "client": self._client_id,
                                             "seq": self._seq})
                    resp = _recv_frame(self._sock)
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
            else:
                raise ConnectionError(
                    f"tracker RPC {method} failed after "
                    f"{self._retries} attempts: {last_err}")
        if not resp.get("ok"):
            raise RuntimeError(f"tracker RPC {method}: {resp.get('error')}")
        return resp.get("value")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def __getattr__(self, name: str):
        if name in ALLOWED_METHODS:
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)
