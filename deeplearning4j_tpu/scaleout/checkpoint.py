"""Checkpoint / resume.

Parity: reference ModelSavingActor + DefaultModelSaver.java:34-70
(serialize model to `nn-model.bin`, timestamp-rename the prior file) and the
canonical checkpoint constructor `MultiLayerNetwork(confJson, params)`
(MultiLayerNetwork.java:91) — i.e. checkpoint = (JSON config, packed param
vector). The reference never checkpoints optimizer state or data position
(SURVEY §5); we do: a checkpoint here is
(conf_json, packed params, updater state pytree, data-iterator position,
user metadata), which makes distributed resume deterministic.

Format: a single file holding a pickled dict of numpy arrays + JSON strings.
(On a real pod this file lands on GCS; the writer below only assumes a
filesystem path. An orbax-backed saver can implement the same two calls.)
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

import jax
import numpy as np


def _to_numpy_tree(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


class ModelSaver:
    def save(self, network, **extra) -> str:
        raise NotImplementedError


class DefaultModelSaver(ModelSaver):
    """Save to a local path, timestamp-renaming any prior checkpoint
    (reference DefaultModelSaver.java:66-70)."""

    def __init__(self, path: str = "nn-model.ckpt", keep_old: bool = True):
        self.path = path
        self.keep_old = keep_old

    def _write(self, payload: Dict[str, Any]) -> str:
        """Timestamp-rename any prior checkpoint, then atomically publish."""
        if self.keep_old and os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.{int(time.time() * 1000)}")
        tmp = f"{self.path}.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, self.path)
        return self.path

    @staticmethod
    def _payload(*, conf_json, params, updater_state=None,
                 iteration_count=0, iterator_position=None, metadata=None):
        return {
            "format_version": 1,
            "conf_json": conf_json,
            "params": np.asarray(params),
            "updater_state": updater_state,
            "iteration_count": iteration_count,
            "iterator_position": iterator_position,
            "metadata": metadata or {},
            "saved_at": time.time(),
        }

    def save(self, network, *, iterator_position: Optional[int] = None,
             metadata: Optional[Dict[str, Any]] = None) -> str:
        return self._write(self._payload(
            conf_json=network.to_json(),
            params=network.params(),
            updater_state=(_to_numpy_tree(network._updater_state)
                           if network._updater_state is not None else None),
            iteration_count=network._iteration_count,
            iterator_position=iterator_position,
            metadata=metadata,
        ))

    def save_current(self, params, *, conf_json: Optional[str] = None,
                     metadata: Optional[Dict[str, Any]] = None) -> str:
        """Checkpoint a packed parameter vector directly — the runtime-level
        save path (DistributedRuntime periodic checkpoints). Loadable by
        `load_checkpoint` when conf_json is provided."""
        return self._write(self._payload(
            conf_json=conf_json, params=params, metadata=metadata))


def load_checkpoint(path: str):
    """Restore a MultiLayerNetwork (+ optimizer state) from a checkpoint.

    Returns (network, info) where info carries iterator_position/metadata
    for the caller to restore data-pipeline state.
    """
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("conf_json") is None:
        raise ValueError(
            f"Checkpoint {path} has no conf_json (params-only runtime "
            "checkpoint saved without a config); rebuild the network from "
            "its config and call set_parameters(payload['params']) instead")
    net = MultiLayerNetwork.from_config_json(payload["conf_json"],
                                             params=payload["params"])
    if payload.get("updater_state") is not None:
        import jax.numpy as jnp
        net._updater_state = jax.tree_util.tree_map(
            jnp.asarray, payload["updater_state"])
    net._iteration_count = payload.get("iteration_count", 0)
    info = {
        "iterator_position": payload.get("iterator_position"),
        "metadata": payload.get("metadata", {}),
        "saved_at": payload.get("saved_at"),
    }
    return net, info
