"""Checkpoint / resume.

Parity: reference ModelSavingActor + DefaultModelSaver.java:34-70
(serialize model to `nn-model.bin`, timestamp-rename the prior file) and the
canonical checkpoint constructor `MultiLayerNetwork(confJson, params)`
(MultiLayerNetwork.java:91) — i.e. checkpoint = (JSON config, packed param
vector). The reference never checkpoints optimizer state or data position
(SURVEY §5); we do: a checkpoint here is
(conf_json, packed params, updater state pytree, data-iterator position,
user metadata), which makes distributed resume deterministic.

Format: a single `.npz` file — arrays stored as plain npy members plus a
JSON manifest describing the pytree structure. Nothing is unpickled on
load (`allow_pickle=False`), so loading a checkpoint from a shared/cloud
path is safe: worst case is a ValueError, never code execution. (On a real
pod this file lands on GCS; the writer below only assumes a filesystem
path. An orbax-backed saver can implement the same two calls.)

This single-file format is now the COMPATIBILITY tier: the production
path is the sharded async directory format in
`deeplearning4j_tpu.checkpoint` (per-device shard files, atomic commit
marker, background writer, cross-topology resharded restore —
docs/CHECKPOINTS.md). `load_checkpoint` below transparently loads both.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.optimize.updater import UpdaterState

#: NamedTuple node types that may appear in checkpointed pytrees.
_NAMEDTUPLES = {"UpdaterState": UpdaterState}


def register_namedtuple(cls) -> None:
    """Allow `cls` (a NamedTuple type) in checkpoint payload pytrees —
    round-trips by field name through the manifest. Modules defining
    checkpointable carries (e.g. optimize.guardian.GuardianState) call
    this at import time rather than this module importing them (which
    would invert the dependency)."""
    _NAMEDTUPLES[cls.__name__] = cls


def _encode_tree(obj, arrays: Dict[str, np.ndarray]):
    """Encode a pytree of arrays/scalars/containers into a JSON-able
    manifest, moving every array leaf into `arrays` under a fresh key."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.ndarray, np.generic, jax.Array)):
        arr = np.asarray(obj)
        if arr.dtype.hasobject:
            # np.savez would silently pickle it, and load_payload
            # (allow_pickle=False) could then never read it back — fail at
            # save time, not restore time.
            raise TypeError("Cannot checkpoint object-dtype array")
        key = f"a{len(arrays)}"
        arrays[key] = arr
        return {"__arr__": key}
    if hasattr(obj, "_fields"):  # NamedTuple
        name = type(obj).__name__
        if name not in _NAMEDTUPLES:
            raise TypeError(f"Unregistered NamedTuple in checkpoint: {name}")
        return {"__nt__": name,
                "fields": {f: _encode_tree(getattr(obj, f), arrays)
                           for f in obj._fields}}
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise TypeError(
                    f"Checkpoint dict keys must be str, got {k!r} "
                    f"({type(k).__name__}) — JSON round-trip would rekey it")
        return {"__dict__": {k: _encode_tree(v, arrays)
                             for k, v in obj.items()}}
    if isinstance(obj, tuple):
        return {"__tuple__": [_encode_tree(v, arrays) for v in obj]}
    if isinstance(obj, list):
        return {"__list__": [_encode_tree(v, arrays) for v in obj]}
    raise TypeError(f"Cannot checkpoint object of type {type(obj)!r}")


def _decode_tree(node, arrays):
    if not isinstance(node, dict):
        return node
    if "__arr__" in node:
        return arrays[node["__arr__"]]
    if "__nt__" in node:
        cls = _NAMEDTUPLES[node["__nt__"]]
        return cls(**{f: _decode_tree(v, arrays)
                      for f, v in node["fields"].items()})
    if "__dict__" in node:
        return {k: _decode_tree(v, arrays) for k, v in node["__dict__"].items()}
    if "__tuple__" in node:
        return tuple(_decode_tree(v, arrays) for v in node["__tuple__"])
    if "__list__" in node:
        return [_decode_tree(v, arrays) for v in node["__list__"]]
    raise ValueError(f"Malformed checkpoint manifest node: {node!r}")


def dump_payload(payload: Dict[str, Any]) -> bytes:
    """Serialize a checkpoint payload dict to npz bytes (no pickle)."""
    arrays: Dict[str, np.ndarray] = {}
    manifest = _encode_tree(payload, arrays)
    buf = io.BytesIO()
    np.savez(buf, __manifest__=np.frombuffer(
        json.dumps(manifest).encode(), np.uint8), **arrays)
    return buf.getvalue()


def load_payload(data: bytes) -> Dict[str, Any]:
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        manifest = json.loads(bytes(z["__manifest__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__manifest__"}
    return _decode_tree(manifest, arrays)


def _to_numpy_tree(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


class ModelSaver:
    def save(self, network, **extra) -> str:
        raise NotImplementedError


class DefaultModelSaver(ModelSaver):
    """Save to a local path, timestamp-renaming any prior checkpoint
    (reference DefaultModelSaver.java:66-70)."""

    def __init__(self, path: str = "nn-model.ckpt", keep_old: bool = True):
        self.path = path
        self.keep_old = keep_old

    def _write(self, payload: Dict[str, Any]) -> str:
        """Timestamp-rename any prior checkpoint, then atomically publish."""
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        if self.keep_old and os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.{int(time.time() * 1000)}")
        tmp = f"{self.path}.tmp"
        with open(tmp, "wb") as f:
            f.write(dump_payload(payload))
        os.replace(tmp, self.path)
        return self.path

    @staticmethod
    def _payload(*, conf_json, params, updater_state=None,
                 iteration_count=0, iterator_position=None, metadata=None):
        return {
            "format_version": 2,
            "conf_json": conf_json,
            "params": np.asarray(params),
            "updater_state": updater_state,
            "iteration_count": iteration_count,
            "iterator_position": iterator_position,
            "metadata": metadata or {},
            "saved_at": time.time(),
        }

    def save(self, network, *, iterator_position: Optional[int] = None,
             metadata: Optional[Dict[str, Any]] = None) -> str:
        return self._write(self._payload(
            conf_json=network.to_json(),
            params=network.params(),
            updater_state=(_to_numpy_tree(network._updater_state)
                           if network._updater_state is not None else None),
            iteration_count=network._iteration_count,
            iterator_position=iterator_position,
            metadata=metadata,
        ))

    def save_current(self, params, *, conf_json: Optional[str] = None,
                     iterator_position: Optional[int] = None,
                     metadata: Optional[Dict[str, Any]] = None) -> str:
        """Checkpoint a packed parameter vector directly — the runtime-level
        save path (DistributedRuntime periodic checkpoints). Loadable by
        `load_checkpoint` when conf_json is provided;
        `iterator_position` is the job-stream resume cursor (same
        first-class field the network-level save uses)."""
        return self._write(self._payload(
            conf_json=conf_json, params=params,
            iterator_position=iterator_position, metadata=metadata))


class UriModelSaver(DefaultModelSaver):
    """ModelSaver that treats its path as a storage URI.

    Parity: reference HdfsModelSaver (hadoop/modelsaving/HdfsModelSaver.java
    — checkpoint to a distributed filesystem path) and S3ModelSaver
    (aws/s3/modelsaver/). The TPU-native artifact plane is GCS
    (SURVEY §5): on a pod, `gs://` buckets are mounted via gcsfuse (or an
    orbax saver is swapped in behind the same two methods), so remote
    schemes resolve to a mount root and everything downstream is plain
    file IO with the same atomic-rename discipline as DefaultModelSaver.

    Supported schemes: `file://` (and bare paths), plus `gs://`, `s3://`,
    `hdfs://` when `mounts` (or the DL4J_TPU_ARTIFACT_ROOT env var) maps
    the scheme to a local mount point, e.g.
    {"gs": "/mnt/gcs"} -> gs://bucket/run/ckpt => /mnt/gcs/bucket/run/ckpt.
    """

    REMOTE_SCHEMES = ("gs", "s3", "hdfs")

    def __init__(self, uri: str, keep_old: bool = True,
                 mounts: Optional[Dict[str, str]] = None):
        self.uri = uri
        mounts = dict(mounts or {})
        env_root = os.environ.get("DL4J_TPU_ARTIFACT_ROOT")
        if env_root:
            for scheme in self.REMOTE_SCHEMES:
                mounts.setdefault(scheme, env_root)
        super().__init__(self._resolve(uri, mounts), keep_old=keep_old)

    @classmethod
    def _resolve(cls, uri: str, mounts: Dict[str, str]) -> str:
        scheme, sep, rest = uri.partition("://")
        if not sep:
            return uri  # bare local path
        if scheme == "file":
            return rest if rest.startswith("/") else "/" + rest
        if scheme in cls.REMOTE_SCHEMES:
            root = mounts.get(scheme)
            if not root:
                raise ValueError(
                    f"{scheme}:// checkpoint URI needs a mount point: pass "
                    f"mounts={{'{scheme}': '/mnt/...'}} or set "
                    f"DL4J_TPU_ARTIFACT_ROOT (no direct {scheme} client in "
                    f"this environment)")
            return os.path.join(root, rest)  # _write makedirs at save time
        raise ValueError(f"Unknown checkpoint URI scheme: {scheme}://")


class OrbaxModelSaver(ModelSaver):
    """Orbax-backed checkpointing — the multi-host tier (SURVEY §5:
    "orbax-style checkpoint of (config, params, opt-state, data-iterator
    state) to GCS"). Same payload contract as DefaultModelSaver, but
    arrays go through orbax's TensorStore backend: sharded jax.Arrays
    save/restore without host-gathering (each host writes its shards —
    the ZeRO/TP/PP trainers' sharded states checkpoint directly), the
    directory can be a gs:// bucket, and `max_to_keep` handles rotation
    (the reference's timestamp-rename, DefaultModelSaver.java:34-70).

    Steps are integers; save() auto-increments unless `step=` is given.
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory) \
            if "://" not in directory else directory
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, network, *, step: Optional[int] = None,
             iterator_position: Optional[int] = None, **extra) -> str:
        ocp = self._ocp
        state = {"params": network._params}
        if getattr(network, "_updater_state", None) is not None:
            # orbax round-trips dicts; NamedTuples restore as dicts, so
            # store plain field maps and rebuild on load
            state["updater_state"] = {
                k: {"hist": v.hist, "velocity": v.velocity,
                    "iteration": v.iteration}
                for k, v in network._updater_state.items()}
        meta = {"conf_json": network.conf.to_json(),
                "iterator_position": iterator_position,
                "saved_at": time.time(), "metadata": extra}
        if step is None:
            latest = self._mgr.latest_step()
            step = 0 if latest is None else latest + 1
        self._mgr.save(step, args=ocp.args.Composite(
            state=ocp.args.StandardSave(state),
            meta=ocp.args.JsonSave(meta)))
        self._mgr.wait_until_finished()
        return os.path.join(str(self.directory), str(step))

    def restore(self, step: Optional[int] = None):
        """Returns (network, info) like load_checkpoint: the rebuilt
        MultiLayerNetwork (params + updater state installed) and the
        manifest info dict."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.optimize.updater import UpdaterState

        ocp = self._ocp
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        restored = self._mgr.restore(step, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(),
            meta=ocp.args.JsonRestore()))
        meta = restored["meta"]
        state = restored["state"]
        net = MultiLayerNetwork.from_config_json(meta["conf_json"])
        net._params = jax.tree_util.tree_map(jnp_asarray, state["params"])
        upd = state.get("updater_state")
        if upd is not None:
            net._updater_state = {
                k: UpdaterState(hist=v["hist"], velocity=v["velocity"],
                                iteration=v["iteration"])
                for k, v in upd.items()}
        info = {"conf_json": meta["conf_json"],
                "iterator_position": meta.get("iterator_position"),
                "saved_at": meta.get("saved_at"),
                "metadata": meta.get("metadata", {}),
                "step": step}
        return net, info

    def close(self) -> None:
        self._mgr.close()


def jnp_asarray(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


def load_checkpoint(path: str):
    """Restore a MultiLayerNetwork (+ optimizer state) from a checkpoint.

    Returns (network, info) where info carries iterator_position/metadata
    for the caller to restore data-pipeline state.

    `path` may be a single-file npz checkpoint (this module's format, the
    compatibility shim) or a sharded checkpoint directory
    (deeplearning4j_tpu.checkpoint, format_version 3) — directories
    delegate to the resharded loader, which reassembles global arrays
    from per-device shards and restores onto ANY topology.
    """
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if os.path.isdir(path):
        from deeplearning4j_tpu.checkpoint import restore_network

        return restore_network(path)
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] == b"\x80\x04" or not data.startswith(b"PK"):
        raise ValueError(
            f"Checkpoint {path} is not in the npz format (format_version 2). "
            "v1 checkpoints were pickle streams, which are no longer loaded "
            "(arbitrary-code-execution risk on shared paths); re-save from "
            "the run that produced it, or convert offline with a trusted "
            "pickle.load + DefaultModelSaver.")
    payload = load_payload(data)
    if payload.get("conf_json") is None:
        raise ValueError(
            f"Checkpoint {path} has no conf_json (params-only runtime "
            "checkpoint saved without a config); rebuild the network from "
            "its config and call set_parameters(payload['params']) instead")
    net = MultiLayerNetwork.from_config_json(payload["conf_json"],
                                             params=payload["params"])
    if payload.get("updater_state") is not None:
        import jax.numpy as jnp
        net._updater_state = jax.tree_util.tree_map(
            jnp.asarray, payload["updater_state"])
    net._iteration_count = payload.get("iteration_count", 0)
    info = {
        "iterator_position": payload.get("iterator_position"),
        "metadata": payload.get("metadata", {}),
        "saved_at": payload.get("saved_at"),
    }
    return net, info
