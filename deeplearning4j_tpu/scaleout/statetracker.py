"""Cluster-coordination state tracker.

Parity: reference StateTracker.java:43 (~60 methods: worker registry,
heartbeats, job assignment, update collection, current-model storage,
replication flags, counters, generic KV, early-stop state, mini-batch
sizing) and its Hazelcast implementation BaseHazelCastStateTracker.java
(heartbeats :909, jobs :833, updates :423, current model IAtomicReference
:76, early-stop fields :70-93, removeWorker :875).

TPU-native design: one thread-safe in-memory implementation. On a TPU pod
the data plane never goes through the tracker (collectives own it); the
tracker is pure control state, so a single coordinator host (or
jax.distributed's coordination service for multi-host) replaces the
Hazelcast replicated-map cluster. The interface is kept so a gRPC/etcd
implementation can be swapped in without touching the runtime.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.scaleout.api import (
    InMemoryUpdateSaver,
    Job,
    UpdateSaver,
)


class InMemoryStateTracker:
    """Thread-safe in-process StateTracker (embedded-Hazelcast equivalent,
    the reference's test-tier tracker, BaseTestDistributed.java:32-95)."""

    def __init__(self, update_saver: Optional[UpdateSaver] = None,
                 heartbeat_timeout: float = 120.0):
        self._lock = threading.RLock()
        self._workers: Dict[str, float] = {}  # id -> registration time
        self._heartbeats: Dict[str, float] = {}
        self._jobs: Dict[str, Job] = {}
        self._updates: List[str] = []  # worker ids with pending updates
        self._update_saver = update_saver or InMemoryUpdateSaver()
        self._current: Any = None  # the global model (packed params)
        self._needs_replicate: Dict[str, bool] = {}
        self._counters: Dict[str, float] = {}
        self._kv: Dict[str, Any] = {}
        self._done = False
        self.heartbeat_timeout = heartbeat_timeout
        # early-stop state (reference BaseHazelCastStateTracker.java:70-93)
        self._initial_patience = 40.0
        self._patience = 40.0
        self._best_loss = float("inf")
        self._early_stop = False
        self._improvement_threshold = 1e-4
        # mini-batch sizing (reference inputSplit)
        self._batch_size: Optional[int] = None

    # ------------------------------------------------------- worker registry
    def add_worker(self, worker_id: str) -> None:
        with self._lock:
            now = time.time()
            new = worker_id not in self._workers
            self._workers.setdefault(worker_id, now)
            self._heartbeats[worker_id] = now
            if new and self._current is not None:
                # late joiner must pull the current global model before
                # training (reference WorkerActor replication on join)
                self._needs_replicate[worker_id] = True

    def remove_worker(self, worker_id: str) -> Optional[Job]:
        """Evict a worker; returns its in-flight job (if any) so the caller
        can reroute it to a live worker (reference removeWorker :875-880 +
        MasterActor stale-job requeue :117-131)."""
        with self._lock:
            self._workers.pop(worker_id, None)
            self._heartbeats.pop(worker_id, None)
            orphan = self._jobs.pop(worker_id, None)
            self._needs_replicate.pop(worker_id, None)
            return orphan

    def workers(self) -> List[str]:
        with self._lock:
            return list(self._workers)

    def heartbeat(self, worker_id: str) -> None:
        with self._lock:
            if worker_id not in self._workers:  # re-register (elasticity)
                self._workers[worker_id] = time.time()
                if self._current is not None:
                    self._needs_replicate[worker_id] = True
            self._heartbeats[worker_id] = time.time()

    def heartbeats(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._heartbeats)

    def stale_workers(self, now: Optional[float] = None) -> List[str]:
        """Workers whose heartbeat is older than the timeout
        (reference MasterActor eviction, MasterActor.java:137-160)."""
        now = now if now is not None else time.time()
        with self._lock:
            return [w for w, hb in self._heartbeats.items()
                    if now - hb >= self.heartbeat_timeout]

    # ------------------------------------------------------- job assignment
    def add_job(self, job: Job) -> None:
        with self._lock:
            self._jobs[job.worker_id] = job

    def job_for(self, worker_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(worker_id)

    def clear_job(self, worker_id: str) -> None:
        with self._lock:
            self._jobs.pop(worker_id, None)

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    # ----------------------------------------------------- update collection
    def add_update(self, worker_id: str, update: Any) -> None:
        """Record a worker result (reference addUpdate :423 — spills through
        the UpdateSaver rather than holding params in tracker memory)."""
        with self._lock:
            self._update_saver.save(worker_id, update)
            if worker_id not in self._updates:
                self._updates.append(worker_id)

    def worker_updates(self) -> List[str]:
        with self._lock:
            return list(self._updates)

    def load_update(self, worker_id: str) -> Any:
        return self._update_saver.load(worker_id)

    def clear_update(self, worker_id: str) -> None:
        """Drop ONE worker's pending update — used after aggregation so
        updates that arrive mid-aggregation are never lost."""
        with self._lock:
            if worker_id in self._updates:
                self._updates.remove(worker_id)
            self._update_saver.delete(worker_id)

    def clear_updates(self) -> None:
        with self._lock:
            self._updates.clear()
            self._update_saver.clear()

    def update_saver(self) -> UpdateSaver:
        return self._update_saver

    # ------------------------------------------------------- current model
    def set_current(self, model: Any) -> None:
        """Store the global model (reference IAtomicReference "master" :76)."""
        with self._lock:
            self._current = model
            for w in self._workers:
                self._needs_replicate[w] = True

    def get_current(self) -> Any:
        with self._lock:
            return self._current

    def needs_replicate(self, worker_id: str) -> bool:
        with self._lock:
            return self._needs_replicate.get(worker_id, False)

    def done_replicating(self, worker_id: str) -> None:
        with self._lock:
            self._needs_replicate[worker_id] = False

    # ----------------------------------------------------------- counters/KV
    def increment(self, key: str, by: float = 1.0) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + by

    def count(self, key: str) -> float:
        with self._lock:
            return self._counters.get(key, 0.0)

    def counters(self) -> Dict[str, float]:
        """All counters at once (status/observability surface)."""
        with self._lock:
            return dict(self._counters)

    def define(self, key: str, value: Any) -> None:
        with self._lock:
            self._kv[key] = value

    def get(self, key: str) -> Any:
        with self._lock:
            return self._kv.get(key)

    # ------------------------------------------------------------ early stop
    def set_patience(self, patience: float) -> None:
        with self._lock:
            self._initial_patience = patience
            self._patience = patience

    def patience(self) -> float:
        with self._lock:
            return self._patience

    def report_loss(self, loss: float) -> None:
        """Track best loss; trip early-stop when no improvement consumes
        the remaining patience (reference patience/bestLoss fields)."""
        with self._lock:
            if loss < self._best_loss - self._improvement_threshold:
                self._best_loss = loss
                self._patience = self._initial_patience  # full reset
            else:
                self._patience -= 1.0
                if self._patience <= 0:
                    self._early_stop = True

    def best_loss(self) -> float:
        with self._lock:
            return self._best_loss

    def early_stop(self) -> bool:
        with self._lock:
            return self._early_stop

    # ------------------------------------------------------------- lifecycle
    def input_split(self, batch_size: int) -> None:
        with self._lock:
            self._batch_size = batch_size

    def batch_size(self) -> Optional[int]:
        with self._lock:
            return self._batch_size

    def finish(self) -> None:
        with self._lock:
            self._done = True

    def is_done(self) -> bool:
        with self._lock:
            return self._done

    def shutdown(self) -> None:
        self.finish()
