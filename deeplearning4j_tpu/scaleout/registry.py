"""Config registry: cluster bootstrap configuration.

Parity: reference scaleout-zookeeper — `ZooKeeperConfigurationRegister`
stores a serialized Configuration at a path derived from (host, port)
(ZooKeeperConfigurationRegister.java:56,:100) and
`ZookeeperConfigurationRetriever.retrieve` reads it back (:38,:59);
`ZookeeperPathBuilder` builds the node path.

TPU-native design: ZooKeeper earns its keep through watches and leader
election, none of which this control plane needs — runs are launched by a
coordinator that already knows the membership (the reference itself only
uses ZK as a blob store for the startup Configuration). So the registry
is a directory of atomically-written JSON files on any shared filesystem
(NFS/GCS-fuse on a real pod), keyed the same way ZK paths were. A
launched worker needs exactly one thing: the run's configuration, which
carries the tracker endpoint and performer wiring.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional


class ConfigRegistry:
    """Register/retrieve run configurations by (host, port) or run name."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # path semantics mirror ZookeeperPathBuilder: one node per (host, port)
    def _path(self, host: str, port: int) -> str:
        safe = host.replace(os.sep, "_").replace(":", "_")
        return os.path.join(self.root, f"{safe}_{port}.json")

    def register(self, host: str, port: int,
                 configuration: Dict[str, Any]) -> str:
        """Atomically publish a configuration (reference register :100)."""
        path = self._path(host, port)
        payload = {"host": host, "port": port, "registered_at": time.time(),
                   "configuration": configuration}
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path

    def retrieve(self, host: str, port: int) -> Dict[str, Any]:
        """reference ZookeeperConfigurationRetriever.retrieve :59."""
        path = self._path(host, port)
        if not os.path.exists(path):
            raise KeyError(f"no configuration registered for "
                           f"{host}:{port} under {self.root}")
        with open(path) as f:
            return json.load(f)["configuration"]

    def wait_for(self, host: str, port: int,
                 timeout: float = 30.0) -> Dict[str, Any]:
        """Block until a configuration appears (workers may start before
        the master has registered)."""
        deadline = time.time() + timeout
        while True:
            try:
                return self.retrieve(host, port)
            except KeyError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)

    def unregister(self, host: str, port: int) -> None:
        path = self._path(host, port)
        if os.path.exists(path):
            os.unlink(path)

    def entries(self) -> List[Dict[str, Any]]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if name.endswith(".json"):
                with open(os.path.join(self.root, name)) as f:
                    out.append(json.load(f))
        return out

    # ------------------------------------------------- run-name convenience
    def register_run(self, run_name: str,
                     configuration: Dict[str, Any]) -> str:
        return self.register(f"run-{run_name}", 0, configuration)

    def retrieve_run(self, run_name: str,
                     timeout: Optional[float] = None) -> Dict[str, Any]:
        if timeout:
            return self.wait_for(f"run-{run_name}", 0, timeout)
        return self.retrieve(f"run-{run_name}", 0)

    def unregister_run(self, run_name: str) -> None:
        self.unregister(f"run-{run_name}", 0)
