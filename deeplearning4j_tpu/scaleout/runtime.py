"""In-process distributed runtime: master/worker choreography.

Parity: reference Akka runtime (SURVEY §2.3/§3.2) —
`DeepLearning4jDistributed` (runner), `MasterActor` (1 s heartbeat poll:
workRouter.sendWork -> nextBatch; stale-job reaping; 120 s worker eviction;
DoneMessage -> aggregate updates -> setCurrent), `WorkerActor` (1 s
heartbeat that re-registers, jobFor -> perform -> addUpdate -> clearJob,
replicate current model when needsReplicate), `BatchActor` (hand the next
mini-batch job to each free worker), `ModelSavingActor` ("save" topic).

TPU-native design: actors/Hazelcast become plain threads + the in-memory
StateTracker — the whole runtime runs embedded in one process (the
reference's own test tier, BaseTestDistributed). The heavy math still
happens on the accelerator inside each performer's `fit`. On a real pod
this layer coordinates SLICES over DCN (each "worker" = one slice running
`parallel.DataParallelTrainer` internally); in-slice exchange always rides
ICI collectives, never this queue. Elasticity (stale eviction + late
registration) therefore lives at the multi-slice level, matching how TPU
membership is static within a slice (SURVEY §7 hard parts).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Type

import numpy as np

from deeplearning4j_tpu.scaleout.aggregator import (
    ParameterAveragingAggregator,
)
from deeplearning4j_tpu.scaleout.api import (
    IterativeReduceWorkRouter,
    Job,
    JobIterator,
    WorkerPerformer,
    WorkRouter,
)
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker

log = logging.getLogger(__name__)

#: tracker counter bumped by workers when a job exhausts its retries, so the
#: master's wave barrier can stop waiting for it
JOBS_DROPPED = "_jobs_dropped"


#: bounded job-retry budget shared by every worker variant
MAX_JOB_RETRIES = 3


def perform_job(tracker, worker_id, performer, job, *,
                work_retriever=None, max_retries=MAX_JOB_RETRIES,
                before_perform=None) -> bool:
    """Execute ONE fetched job under the worker contract shared by the
    in-process `_Worker`, the launcher's remote worker, and the
    supervised elastic worker: resolve the payload (WorkRetriever data
    plane), perform, publish the update, clear the job — or requeue it
    with the bounded retry budget, incrementing `JOBS_DROPPED` when the
    budget runs out so the master's exact wave barrier stops waiting.
    `before_perform(job)` runs inside the try (a failure there is a job
    failure — the supervised worker's chaos point). ConnectionError
    propagates: for a remote worker the master being gone is a shutdown
    signal, not a job failure. Returns True when the job performed."""
    try:
        if before_perform is not None:
            before_perform(job)
        if job.work is None and work_retriever is not None:
            # payload travels via the WorkRetriever data plane, not the
            # tracker (reference WorkRetriever.load)
            stored = work_retriever.load(worker_id)
            if stored is not None:
                job.work = stored.work
        performer.perform(job)
        tracker.add_update(worker_id, job.result)
        tracker.clear_job(worker_id)
        if work_retriever is not None:
            work_retriever.clear(worker_id)
        return True
    except ConnectionError:
        raise
    except Exception:  # requeue (bounded), don't kill the loop
        log.exception("worker %s failed job", worker_id)
        tracker.clear_job(worker_id)
        job.retries += 1
        if job.retries < max_retries:
            tracker.add_job(job)
        else:
            log.error("dropping job for %s after %d retries",
                      worker_id, job.retries)
            # the master's exact wave barrier must not wait for an
            # update that will never come
            tracker.increment(JOBS_DROPPED)
        return False


class _Worker(threading.Thread):
    """Worker loop (reference WorkerActor.java:166-215 heartbeat body)."""

    MAX_RETRIES = MAX_JOB_RETRIES

    def __init__(self, worker_id: str, tracker: InMemoryStateTracker,
                 performer: WorkerPerformer, interval: float,
                 work_retriever=None):
        super().__init__(name=f"dl4j-worker-{worker_id}", daemon=True)
        self.worker_id = worker_id
        self.tracker = tracker
        self.performer = performer
        self.interval = interval
        self.work_retriever = work_retriever
        self.performed = 0
        self.paused = threading.Event()  # set => skip heartbeats (fault inj.)

    def run(self):
        tracker, wid = self.tracker, self.worker_id
        if hasattr(self.performer, "bind_tracker"):
            self.performer.bind_tracker(tracker)  # e.g. w2v alpha counter
        tracker.add_worker(wid)
        while not tracker.is_done():
            if self.paused.is_set():
                time.sleep(self.interval)
                continue
            tracker.heartbeat(wid)  # re-registers if evicted (elasticity)
            if tracker.needs_replicate(wid):
                current = tracker.get_current()
                if current is not None:
                    self.performer.update(current)
                tracker.done_replicating(wid)
            job = tracker.job_for(wid)
            if job is not None and job.result is None:
                if perform_job(tracker, wid, self.performer, job,
                               work_retriever=self.work_retriever,
                               max_retries=self.MAX_RETRIES):
                    self.performed += 1
            else:
                time.sleep(self.interval)


class DistributedRuntime:
    """Embedded master + N workers over a StateTracker.

    `performer_factory` builds one WorkerPerformer per worker (the reference's
    WorkerPerformerFactory config key). `sync=True` uses iterative-reduce
    waves (aggregate when ALL workers reported); `sync=False` is hogwild:
    every arriving update merges into the current model immediately
    (reference HogWildWorkRouter + MultiLayerNetwork.merge :1361).
    """

    def __init__(
        self,
        job_iterator: JobIterator,
        performer_factory: Callable[[], WorkerPerformer],
        n_workers: int = 2,
        tracker: Optional[InMemoryStateTracker] = None,
        router_cls: Optional[Type[WorkRouter]] = None,
        heartbeat_interval: float = 0.01,
        model_saver=None,
        save_every_waves: int = 0,
        initial_params: Optional[np.ndarray] = None,
        aggregator_factory: Optional[Callable] = None,
        work_retriever=None,
    ):
        self.job_iterator = job_iterator
        self.tracker = tracker or InMemoryStateTracker()
        self.n_workers = n_workers
        # performer_factory=None => workers live in other processes
        # (MultiProcessMaster) and bring their own performers
        self.performers = ([performer_factory() for _ in range(n_workers)]
                           if performer_factory is not None else [])
        self.router = (router_cls or IterativeReduceWorkRouter)(self.tracker)
        # Declarative router policy: barrier-style routers aggregate in
        # waves; async routers merge updates as they arrive, with
        # send_work() gating each dispatch (reference WorkRouter.sendWork).
        self.sync = self.router.synchronous
        self.interval = heartbeat_interval
        self.model_saver = model_saver
        self.save_every_waves = save_every_waves
        self.workers: List[_Worker] = []
        self.work_retriever = work_retriever
        self.aggregator_factory = (aggregator_factory
                                   or ParameterAveragingAggregator)
        self.waves = 0
        #: jobs pulled from the iterator so far
        self.jobs_consumed = 0
        #: updates folded into the published model (one per job); see
        #: _resume_cursor for how the checkpointed position is derived
        self.jobs_aggregated = 0
        #: stream positions of every update folded into the published
        #: model, in fold order — the batch-index trace the elastic
        #: drills audit ("no example dropped or double-trained")
        self.folded_seqs: List[int] = []
        #: last job-stream seq dispatched to each worker: aggregation
        #: folds in SEQ order so the averaged sum is a function of the
        #: wave's job set alone, never of completion order or of which
        #: (possibly respawned) worker computed which job — what makes
        #: an elastic run bit-identical to an uninterrupted one
        self._seq_of: Dict[str, int] = {}
        self._orphan_jobs: List[Job] = []  # evicted workers' in-flight jobs
        # Exact wave membership (reference IterativeReduceWorkRouter.java:46-57
        # barrier): number of jobs dispatched into the current wave. The wave
        # completes only when that many updates arrived — an eviction mid-wave
        # re-forms the wave (its orphan job is re-served to a live worker and
        # the barrier keeps waiting) instead of silently shrinking it.
        self._wave_size = 0
        self._wave_dropped_base = 0  # JOBS_DROPPED count when wave opened
        if initial_params is not None:
            self.tracker.set_current(np.asarray(initial_params))

    # ------------------------------------------------------------ lifecycle
    def start_workers(self):
        if self.workers:  # idempotent: run() also calls this, and two
            return        # threads sharing one performer would race
        for i, performer in enumerate(self.performers):
            w = _Worker(f"worker-{i}", self.tracker, performer, self.interval,
                        work_retriever=self.work_retriever)
            self.workers.append(w)
            w.start()

    def _free_workers(self) -> List[str]:
        assigned = {j.worker_id for j in self.tracker.jobs()}
        pending = set(self.tracker.worker_updates())
        return [w for w in self.tracker.workers()
                if w not in assigned and w not in pending]

    def _dispatch_wave(self, orphans_only: bool = False) -> int:
        """Hand jobs to free workers. `orphans_only` re-serves evicted
        members' jobs into an OPEN wave without pulling new work from the
        iterator (the re-formed wave keeps its original membership)."""
        sent = 0
        for wid in self._free_workers():
            if self._orphan_jobs:  # re-serve evicted workers' jobs first
                job = self._orphan_jobs.pop()
                job.worker_id = wid
                job.result = None
            elif not orphans_only and self.job_iterator.has_next():
                try:
                    job = self.job_iterator.next(wid)
                except StopIteration:
                    break
                if job.seq is None:
                    job.seq = self.jobs_consumed
                self.jobs_consumed += 1
            else:
                break
            if job.seq is not None:
                self._seq_of[wid] = job.seq
            if self.work_retriever is not None and job.work is not None:
                # data plane: payload goes through the WorkRetriever
                # (reference BatchActor routeJob -> workRetriever.save);
                # the tracker carries only the light descriptor
                self.work_retriever.save(wid, job)
                job = Job(work=None, worker_id=wid, retries=job.retries,
                          seq=job.seq)
            self.router.route_job(job)
            sent += 1
        return sent

    def _has_work(self) -> bool:
        return bool(self._orphan_jobs) or self.job_iterator.has_next()

    def _open_wave(self) -> int:
        """Dispatch a new wave and record its exact membership size."""
        self._wave_dropped_base = self.tracker.count(JOBS_DROPPED)
        self._wave_opened_at = time.monotonic()
        self._wave_size = self._dispatch_wave()
        return self._wave_size

    def _sync_tick(self, n_updates: int, n_outstanding: int) -> bool:
        """One master poll in iterative-reduce mode; True => job stream
        drained (stop). Exact wave barrier (reference
        IterativeReduceWorkRouter.java:46-57)."""
        if self._wave_size:
            # Open wave: first re-serve any evicted member's job to a
            # live worker (wave re-forms), then hold the barrier until
            # EVERY dispatched job has reported — exact membership,
            # not "whatever jobs happen to remain".
            if self._orphan_jobs:
                sent = self._dispatch_wave(orphans_only=True)
                if not sent and not n_outstanding \
                        and not self._expecting_capacity():
                    # Every surviving member has reported and nobody is
                    # free to take the orphan (live workers all hold
                    # pending updates; re-dispatching to one would
                    # overwrite its update). Close the wave on the
                    # survivors and carry the orphan into the next wave —
                    # it is served first there — instead of spinning
                    # until the run timeout.
                    log.warning(
                        "wave of %d: %d orphan job(s) undeliverable, "
                        "closing wave on survivors and carrying them over",
                        self._wave_size, len(self._orphan_jobs))
                    self._aggregate_and_publish()
                    self._wave_size = 0
            elif self._wave_complete(n_updates, n_outstanding):
                self._aggregate_and_publish()
                self._wave_size = 0
        elif n_updates and not n_outstanding:
            # stray updates with no open wave — e.g. an evicted worker
            # re-registered and completed its old job after the wave it
            # belonged to already closed. Fold them in (at-least-once
            # semantics; averaging tolerates the duplicate batch) so the
            # loop can't livelock on an update nobody is waiting for.
            self._aggregate_and_publish()
        elif not n_updates and not n_outstanding:
            if not self._has_work():
                return True
            if (self._expecting_capacity()
                    and len(self._free_workers()) < self.n_workers):
                # a replacement worker is on its way: hold the next
                # wave until the pool is whole again, so wave
                # composition matches the uninterrupted schedule
                # (capacity that never arrives flips the flag off and
                # the wave opens on the survivors)
                return False
            self._open_wave()
        return False

    def _wave_complete(self, n_updates: int, n_outstanding: int) -> bool:
        """True when every job dispatched into the current wave has either
        reported an update or been dropped after exhausting retries.
        Evicted members don't shrink the wave: their orphan jobs are
        re-served (`_dispatch_wave(orphans_only=True)`) and the barrier
        keeps waiting for their updates."""
        if n_outstanding or self._orphan_jobs:
            return False
        dropped = (self.tracker.count(JOBS_DROPPED)
                   - getattr(self, "_wave_dropped_base", 0))
        return n_updates + dropped >= self._wave_size

    def _aggregate_and_publish(self):
        """Average pending updates into the new global model (reference
        MasterActor DoneMessage handling :219-330). Only the snapshot of
        updates that was aggregated is cleared — updates arriving
        mid-aggregation survive for the next round.

        Updates fold in canonical JOB-SEQ order (not arrival order): a
        float sum depends on operand order, so folding by the stream
        position of the job each update answers makes the published
        params a pure function of the wave's job set — an evicted
        worker's orphan job redone by a respawned peer aggregates bit-
        identically to the uninterrupted run."""
        snapshot = self.tracker.worker_updates()
        if not snapshot:
            return
        inf = float("inf")
        snapshot = sorted(snapshot,
                          key=lambda w: (self._seq_of.get(w, inf), w))
        agg = self.aggregator_factory()
        for wid in snapshot:
            update = self.tracker.load_update(wid)
            if update is not None:
                agg.accumulate(Job(work=None, worker_id=wid, result=update))
        averaged = agg.aggregate()
        if averaged is None:
            return
        current = self.tracker.get_current()
        if hasattr(agg, "apply"):
            # aggregators with custom publication semantics (delta
            # application, counter merge — the distributed NLP performers)
            new = agg.apply(current, averaged)
        elif current is not None and self.sync:
            # epoch-wave averaging: replace (all replicas started from
            # `current`, so the average IS the merged model)
            new = averaged
        elif current is not None:
            # hogwild merge: current += (update_avg - current)/n
            n = max(1, len(self.tracker.workers()))
            new = np.asarray(current) + (averaged - np.asarray(current)) / n
        else:
            new = averaged
        self.tracker.set_current(new)
        for wid in snapshot:
            self.tracker.clear_update(wid)
            seq = self._seq_of.pop(wid, None)
            if seq is not None:
                self.folded_seqs.append(seq)
        self.waves += 1
        self.jobs_aggregated += len(snapshot)
        if (self.model_saver is not None and self.save_every_waves
                and self.waves % self.save_every_waves == 0):
            self._save()

    def _resume_cursor(self) -> int:
        """Job-stream position a resumed master may safely seek() to.

        Never overshoots work that is NOT in the saved params: counts
        only updates actually folded in (jobs_aggregated) plus jobs
        finally dropped after retries (re-running those would fail
        again), capped at jobs pulled — the cap keeps at-least-once
        duplicates (an evicted worker's late update folding alongside
        the orphan's redo) from skipping never-trained batches.
        Undershoot merely re-trains a batch, which parameter averaging
        tolerates; overshoot would silently lose training data."""
        dropped = self.tracker.count(JOBS_DROPPED)
        return int(min(self.jobs_consumed,
                       self.jobs_aggregated + dropped))

    def _save(self):
        """Checkpoint the current averaged model (reference ModelSavingActor
        "save" topic). The saver's save_current gets the packed params plus
        the conf JSON so the checkpoint is self-describing, and the
        first-class iterator_position resume cursor."""
        conf_json = getattr(self, "conf_json", None)
        if conf_json is None and self.performers:
            conf_json = getattr(self.performers[0], "conf_json", None)
        self.model_saver.save_current(
            self.tracker.get_current(), conf_json=conf_json,
            iterator_position=self._resume_cursor(),
            metadata={"waves": self.waves})

    def _tick(self):
        """Per-poll supervision hook, called once per master loop pass
        (including the registration wait). The base runtime does nothing;
        TrainingSupervisor overrides it with process health, respawn,
        straggler, and elastic-resume duties."""

    def _expecting_capacity(self) -> bool:
        """True while replacement workers are known to be on their way
        (the supervisor's respawn pipeline). An open wave holding an
        undeliverable orphan then KEEPS its barrier — the orphan is
        served to the respawned member and the wave re-forms with its
        original membership (what makes the respawn path bit-identical)
        — instead of closing early on the survivors. The base runtime
        has no respawn pipeline, so capacity never arrives: False."""
        return False

    def _evict_stale(self):
        for wid in self.tracker.stale_workers():
            log.warning("evicting stale worker %s", wid)
            orphan = self.tracker.remove_worker(wid)
            if orphan is not None and orphan.result is None:
                work = orphan.work
                if work is None and self.work_retriever is not None:
                    # payload lives in the WorkRetriever under the evicted
                    # worker's id; pull it back so the re-dispatch can
                    # re-save it under the new assignee
                    stored = self.work_retriever.load(wid)
                    if stored is not None:
                        work = stored.work
                    self.work_retriever.clear(wid)
                # fresh Job: the evicted worker may still be mutating the
                # old instance; sharing it would let a late completion
                # poison the reassigned copy
                self._orphan_jobs.append(Job(work=work,
                                             worker_id=orphan.worker_id,
                                             retries=orphan.retries,
                                             seq=orphan.seq))

    # ---------------------------------------------------------------- train
    def run(self, timeout: float = 120.0) -> np.ndarray:
        """Run to completion of the job stream; returns final averaged
        params (reference DeepLearning4jDistributed.train)."""
        self.start_workers()
        deadline = time.time() + timeout
        # wait for registration
        while len(self.tracker.workers()) < self.n_workers:
            self._tick()  # a crashed spawn must be respawnable even here
            if time.time() > deadline:
                raise TimeoutError("workers failed to register")
            time.sleep(self.interval)

        while time.time() < deadline:
            self._tick()
            self._evict_stale()
            n_updates = len(self.tracker.worker_updates())
            n_outstanding = len(self.tracker.jobs())
            if self.sync:
                if self._sync_tick(n_updates, n_outstanding):
                    break
            else:
                if n_updates:
                    self._aggregate_and_publish()
                if self._has_work():
                    if self.router.send_work():
                        self._dispatch_wave()
                elif not n_outstanding and not n_updates:
                    break
            if self.tracker.early_stop():
                log.info("early stop tripped")
                break
            time.sleep(self.interval)

        # drain any final updates
        if self.tracker.worker_updates():
            self._aggregate_and_publish()
        self.tracker.finish()
        for w in self.workers:
            w.join(timeout=5.0)
        return self.tracker.get_current()
