"""In-process distributed runtime: master/worker choreography.

Parity: reference Akka runtime (SURVEY §2.3/§3.2) —
`DeepLearning4jDistributed` (runner), `MasterActor` (1 s heartbeat poll:
workRouter.sendWork -> nextBatch; stale-job reaping; 120 s worker eviction;
DoneMessage -> aggregate updates -> setCurrent), `WorkerActor` (1 s
heartbeat that re-registers, jobFor -> perform -> addUpdate -> clearJob,
replicate current model when needsReplicate), `BatchActor` (hand the next
mini-batch job to each free worker), `ModelSavingActor` ("save" topic).

TPU-native design: actors/Hazelcast become plain threads + the in-memory
StateTracker — the whole runtime runs embedded in one process (the
reference's own test tier, BaseTestDistributed). The heavy math still
happens on the accelerator inside each performer's `fit`. On a real pod
this layer coordinates SLICES over DCN (each "worker" = one slice running
`parallel.DataParallelTrainer` internally); in-slice exchange always rides
ICI collectives, never this queue. Elasticity (stale eviction + late
registration) therefore lives at the multi-slice level, matching how TPU
membership is static within a slice (SURVEY §7 hard parts).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Type

import numpy as np

from deeplearning4j_tpu.scaleout.aggregator import (
    ParameterAveragingAggregator,
)
from deeplearning4j_tpu.scaleout.api import (
    IterativeReduceWorkRouter,
    Job,
    JobIterator,
    WorkerPerformer,
    WorkRouter,
)
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker

log = logging.getLogger(__name__)


class _Worker(threading.Thread):
    """Worker loop (reference WorkerActor.java:166-215 heartbeat body)."""

    MAX_RETRIES = 3

    def __init__(self, worker_id: str, tracker: InMemoryStateTracker,
                 performer: WorkerPerformer, interval: float):
        super().__init__(name=f"dl4j-worker-{worker_id}", daemon=True)
        self.worker_id = worker_id
        self.tracker = tracker
        self.performer = performer
        self.interval = interval
        self.performed = 0
        self.paused = threading.Event()  # set => skip heartbeats (fault inj.)

    def run(self):
        tracker, wid = self.tracker, self.worker_id
        if hasattr(self.performer, "bind_tracker"):
            self.performer.bind_tracker(tracker)  # e.g. w2v alpha counter
        tracker.add_worker(wid)
        while not tracker.is_done():
            if self.paused.is_set():
                time.sleep(self.interval)
                continue
            tracker.heartbeat(wid)  # re-registers if evicted (elasticity)
            if tracker.needs_replicate(wid):
                current = tracker.get_current()
                if current is not None:
                    self.performer.update(current)
                tracker.done_replicating(wid)
            job = tracker.job_for(wid)
            if job is not None and job.result is None:
                try:
                    self.performer.perform(job)
                    tracker.add_update(wid, job.result)
                    self.performed += 1
                    tracker.clear_job(wid)
                except Exception:  # requeue (bounded), don't kill the loop
                    log.exception("worker %s failed job", wid)
                    tracker.clear_job(wid)
                    job.retries += 1
                    if job.retries < self.MAX_RETRIES:
                        tracker.add_job(job)
                    else:
                        log.error("dropping job for %s after %d retries",
                                  wid, job.retries)
            else:
                time.sleep(self.interval)


class DistributedRuntime:
    """Embedded master + N workers over a StateTracker.

    `performer_factory` builds one WorkerPerformer per worker (the reference's
    WorkerPerformerFactory config key). `sync=True` uses iterative-reduce
    waves (aggregate when ALL workers reported); `sync=False` is hogwild:
    every arriving update merges into the current model immediately
    (reference HogWildWorkRouter + MultiLayerNetwork.merge :1361).
    """

    def __init__(
        self,
        job_iterator: JobIterator,
        performer_factory: Callable[[], WorkerPerformer],
        n_workers: int = 2,
        tracker: Optional[InMemoryStateTracker] = None,
        router_cls: Optional[Type[WorkRouter]] = None,
        heartbeat_interval: float = 0.01,
        model_saver=None,
        save_every_waves: int = 0,
        initial_params: Optional[np.ndarray] = None,
        aggregator_factory: Optional[Callable] = None,
    ):
        self.job_iterator = job_iterator
        self.tracker = tracker or InMemoryStateTracker()
        self.n_workers = n_workers
        # performer_factory=None => workers live in other processes
        # (MultiProcessMaster) and bring their own performers
        self.performers = ([performer_factory() for _ in range(n_workers)]
                           if performer_factory is not None else [])
        self.router = (router_cls or IterativeReduceWorkRouter)(self.tracker)
        # Declarative router policy: barrier-style routers aggregate in
        # waves; async routers merge updates as they arrive, with
        # send_work() gating each dispatch (reference WorkRouter.sendWork).
        self.sync = self.router.synchronous
        self.interval = heartbeat_interval
        self.model_saver = model_saver
        self.save_every_waves = save_every_waves
        self.workers: List[_Worker] = []
        self.aggregator_factory = (aggregator_factory
                                   or ParameterAveragingAggregator)
        self.waves = 0
        self._orphan_jobs: List[Job] = []  # evicted workers' in-flight jobs
        if initial_params is not None:
            self.tracker.set_current(np.asarray(initial_params))

    # ------------------------------------------------------------ lifecycle
    def start_workers(self):
        for i, performer in enumerate(self.performers):
            w = _Worker(f"worker-{i}", self.tracker, performer, self.interval)
            self.workers.append(w)
            w.start()

    def _free_workers(self) -> List[str]:
        assigned = {j.worker_id for j in self.tracker.jobs()}
        pending = set(self.tracker.worker_updates())
        return [w for w in self.tracker.workers()
                if w not in assigned and w not in pending]

    def _dispatch_wave(self) -> int:
        sent = 0
        for wid in self._free_workers():
            if self._orphan_jobs:  # re-serve evicted workers' jobs first
                job = self._orphan_jobs.pop()
                job.worker_id = wid
                job.result = None
            elif self.job_iterator.has_next():
                try:
                    job = self.job_iterator.next(wid)
                except StopIteration:
                    break
            else:
                break
            self.router.route_job(job)
            sent += 1
        return sent

    def _has_work(self) -> bool:
        return bool(self._orphan_jobs) or self.job_iterator.has_next()

    def _aggregate_and_publish(self):
        """Average pending updates into the new global model (reference
        MasterActor DoneMessage handling :219-330). Only the snapshot of
        updates that was aggregated is cleared — updates arriving
        mid-aggregation survive for the next round."""
        snapshot = self.tracker.worker_updates()
        if not snapshot:
            return
        agg = self.aggregator_factory()
        for wid in snapshot:
            update = self.tracker.load_update(wid)
            if update is not None:
                agg.accumulate(Job(work=None, worker_id=wid, result=update))
        averaged = agg.aggregate()
        if averaged is None:
            return
        current = self.tracker.get_current()
        if hasattr(agg, "apply"):
            # aggregators with custom publication semantics (delta
            # application, counter merge — the distributed NLP performers)
            new = agg.apply(current, averaged)
        elif current is not None and self.sync:
            # epoch-wave averaging: replace (all replicas started from
            # `current`, so the average IS the merged model)
            new = averaged
        elif current is not None:
            # hogwild merge: current += (update_avg - current)/n
            n = max(1, len(self.tracker.workers()))
            new = np.asarray(current) + (averaged - np.asarray(current)) / n
        else:
            new = averaged
        self.tracker.set_current(new)
        for wid in snapshot:
            self.tracker.clear_update(wid)
        self.waves += 1
        if (self.model_saver is not None and self.save_every_waves
                and self.waves % self.save_every_waves == 0):
            self._save()

    def _save(self):
        """Checkpoint the current averaged model (reference ModelSavingActor
        "save" topic). The saver's save_current gets the packed params plus
        the conf JSON so the checkpoint is self-describing."""
        conf_json = getattr(self, "conf_json", None)
        if conf_json is None and self.performers:
            conf_json = getattr(self.performers[0], "conf_json", None)
        self.model_saver.save_current(
            self.tracker.get_current(), conf_json=conf_json,
            metadata={"waves": self.waves})

    def _evict_stale(self):
        for wid in self.tracker.stale_workers():
            log.warning("evicting stale worker %s", wid)
            orphan = self.tracker.remove_worker(wid)
            if orphan is not None and orphan.result is None:
                # fresh Job: the evicted worker may still be mutating the
                # old instance; sharing it would let a late completion
                # poison the reassigned copy
                self._orphan_jobs.append(Job(work=orphan.work,
                                             worker_id=orphan.worker_id,
                                             retries=orphan.retries))

    # ---------------------------------------------------------------- train
    def run(self, timeout: float = 120.0) -> np.ndarray:
        """Run to completion of the job stream; returns final averaged
        params (reference DeepLearning4jDistributed.train)."""
        self.start_workers()
        deadline = time.time() + timeout
        # wait for registration
        while len(self.tracker.workers()) < self.n_workers:
            if time.time() > deadline:
                raise TimeoutError("workers failed to register")
            time.sleep(self.interval)

        while time.time() < deadline:
            self._evict_stale()
            n_updates = len(self.tracker.worker_updates())
            n_outstanding = len(self.tracker.jobs())
            if self.sync:
                # wave barrier: aggregate when all outstanding jobs reported
                if n_updates and not n_outstanding:
                    self._aggregate_and_publish()
                elif not n_updates and not n_outstanding:
                    if not self._has_work():
                        break
                    self._dispatch_wave()
            else:
                if n_updates:
                    self._aggregate_and_publish()
                if self._has_work():
                    if self.router.send_work():
                        self._dispatch_wave()
                elif not n_outstanding and not n_updates:
                    break
            if self.tracker.early_stop():
                log.info("early stop tripped")
                break
            time.sleep(self.interval)

        # drain any final updates
        if self.tracker.worker_updates():
            self._aggregate_and_publish()
        self.tracker.finish()
        for w in self.workers:
            w.join(timeout=5.0)
        return self.tracker.get_current()
