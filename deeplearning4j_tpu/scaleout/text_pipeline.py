"""Distributed corpus->vectors pipeline over the multi-process runtime.

Parity: the reference builds Word2Vec vocab DISTRIBUTED before training —
Spark `TextPipeline` (spark/dl4j-spark-nlp/.../text/TextPipeline.java:
tokenize RDD -> word counts -> vocab cache) feeding `Word2VecPerformer`
(nlp/.../scaleout/perform/models/word2vec/Word2VecPerformer.java:88-140),
with `WordCountWorkPerformer` + Counter-merge aggregation as the counting
primitive (nlp/.../scaleout/perform/text/).

TPU-native design: two phases over the SAME control plane —

1. **count**: sentence-batch jobs -> `WordCountWorkPerformer` on worker
   processes -> `WordCountJobAggregator` Counter-merges each wave into
   the tracker's current model; the final merged counts come back to the
   driver.
2. **train**: the driver builds the `VocabCache` (+ Huffman codes) from
   those counts, seeds the packed embedding tables, and runs
   `Word2VecWorkPerformer` jobs whose averaged deltas land on the
   current model — no prebuilt vocab ever enters the run config from
   outside.

Worker processes join each phase by run name (`<run>-vocab`, then
`<run>-train`) via the standard launcher CLI; `ClusterSetup`
(scaleout/provision.py) can start them on provisioned hosts.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.huffman import build_huffman
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.scaleout.api import CollectionJobIterator
from deeplearning4j_tpu.scaleout.launcher import MultiProcessMaster
from deeplearning4j_tpu.scaleout.perform_nlp import (
    DeltaAveragingAggregator,
    Word2VecWorkPerformer,
    WordCountJobAggregator,
)
from deeplearning4j_tpu.scaleout.registry import ConfigRegistry

log = logging.getLogger(__name__)


def vocab_from_counts(counts: Dict[str, float],
                      min_word_frequency: float = 1.0) -> VocabCache:
    """Merged word counts -> VocabCache with Huffman codes (the driver
    half of the reference TextPipeline -> InMemoryLookupCache hand-off)."""
    cache = VocabCache()
    for word, count in counts.items():
        cache.add_token(word, float(count))
    cache.truncate(min_word_frequency)
    build_huffman(cache)
    return cache


def sentence_batches(sentences: Sequence[str], batch: int,
                     passes: int = 1) -> List[List[str]]:
    out = [list(sentences[i:i + batch])
           for i in range(0, len(sentences), batch)]
    return out * passes


class DistributedWord2Vec:
    """Raw corpus -> trained word vectors across worker PROCESSES, with
    the vocab itself built by the cluster (phase 1) rather than shipped
    in from outside.

    The driver (this class) hosts both phase masters; workers join each
    phase's run name (`<run>-vocab`, `<run>-train`) with the standard
    `python -m deeplearning4j_tpu.scaleout.launcher worker` CLI.
    """

    def __init__(self, sentences: Sequence[str], *, run_name: str,
                 registry: ConfigRegistry, n_workers: int = 2,
                 sentences_per_job: int = 100, passes: int = 1,
                 min_word_frequency: float = 1.0, layer_size: int = 100,
                 window: int = 5, negative: int = 0,
                 learning_rate: float = 0.025, batch_pairs: int = 4096,
                 seed: int = 123, host: str = "127.0.0.1",
                 status_port: Optional[int] = None):
        self.sentences = list(sentences)
        self.run_name = run_name
        self.registry = registry
        self.n_workers = n_workers
        self.sentences_per_job = sentences_per_job
        self.passes = passes
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.learning_rate = learning_rate
        self.batch_pairs = batch_pairs
        self.seed = seed
        self.host = host
        self.status_port = status_port
        self.vocab: Optional[VocabCache] = None
        self.counts: Optional[Dict[str, float]] = None

    # ------------------------------------------------------ phase 1: count
    def count_words(self, timeout: float = 120.0) -> Dict[str, float]:
        """Run the word-count phase (`<run>-vocab`); returns merged
        counts once every batch has been counted by some worker."""
        master = MultiProcessMaster(
            CollectionJobIterator(
                sentence_batches(self.sentences, self.sentences_per_job)),
            run_name=f"{self.run_name}-vocab",
            registry=self.registry,
            performer_class=("deeplearning4j_tpu.scaleout.perform_nlp."
                             "WordCountWorkPerformer"),
            n_workers=self.n_workers,
            host=self.host,
            status_port=self.status_port,
            aggregator_factory=WordCountJobAggregator,
        )
        counts = master.run(timeout=timeout)
        if not counts:
            raise RuntimeError("word-count phase produced no counts")
        self.counts = dict(counts)
        log.info("distributed vocab: %d distinct words, %.0f tokens",
                 len(self.counts), sum(self.counts.values()))
        return self.counts

    def build_vocab(self) -> VocabCache:
        if self.counts is None:
            raise ValueError("count_words() first (or pass counts)")
        self.vocab = vocab_from_counts(self.counts, self.min_word_frequency)
        return self.vocab

    # ------------------------------------------------------ phase 2: train
    def _train_conf(self) -> Dict[str, Any]:
        assert self.vocab is not None
        return {
            "vocab": self.vocab.to_dict(),
            "layer_size": self.layer_size,
            "window": self.window,
            "negative": self.negative,
            "learning_rate": self.learning_rate,
            "total_words": self.vocab.total_word_count * self.passes,
            "batch_pairs": self.batch_pairs,
            "seed": self.seed,
        }

    def train(self, timeout: float = 300.0):
        """Run the training phase (`<run>-train`); returns WordVectors
        built from the averaged final tables."""
        if self.vocab is None:
            self.build_vocab()
        conf = self._train_conf()
        seed_performer = Word2VecWorkPerformer()
        seed_performer.setup(conf)
        initial = seed_performer.pack()
        master = MultiProcessMaster(
            CollectionJobIterator(
                sentence_batches(self.sentences, self.sentences_per_job,
                                 self.passes)),
            run_name=f"{self.run_name}-train",
            registry=self.registry,
            performer_class=("deeplearning4j_tpu.scaleout.perform_nlp."
                             "Word2VecWorkPerformer"),
            performer_conf=conf,
            n_workers=self.n_workers,
            host=self.host,
            status_port=self.status_port,
            aggregator_factory=DeltaAveragingAggregator,
            initial_params=initial,
        )
        final = master.run(timeout=timeout)
        if final is None:
            raise RuntimeError("training phase produced no model")
        seed_performer.update(np.asarray(final))
        return seed_performer.word_vectors()

    def fit(self, timeout: float = 300.0):
        """corpus -> counts -> vocab -> vectors (workers must join each
        phase as it opens — e.g. ClusterSetup-provisioned hosts running
        the launcher CLI against `<run>-vocab` then `<run>-train`)."""
        self.count_words(timeout=timeout)
        self.build_vocab()
        return self.train(timeout=timeout)
