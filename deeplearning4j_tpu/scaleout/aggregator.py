"""Result aggregators.

Parity: reference INDArrayAggregator.java:35-59 (sum packed parameter
vectors, divide by count — the parameter-averaging reduce under every
distributed runtime) and IterateAndUpdateImpl (replay UpdateSaver contents
through an aggregator).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from deeplearning4j_tpu.scaleout.api import Job, JobAggregator


class ParameterAveragingAggregator(JobAggregator):
    """Average packed parameter vectors (reference INDArrayAggregator)."""

    def __init__(self):
        self._sum: Optional[np.ndarray] = None
        self._count = 0

    def accumulate(self, job: Job) -> None:
        vec = np.asarray(job.result if isinstance(job, Job) else job)
        if self._sum is None:
            self._sum = vec.astype(np.float64).copy()
        else:
            if vec.shape != self._sum.shape:
                raise ValueError(
                    f"Update shape {vec.shape} != accumulated {self._sum.shape}")
            self._sum += vec
        self._count += 1

    def aggregate(self) -> Optional[np.ndarray]:
        if self._sum is None or self._count == 0:
            return None
        return (self._sum / self._count).astype(np.float32)

    def reset(self) -> None:
        self._sum = None
        self._count = 0


def iterate_and_update(tracker, aggregator: JobAggregator) -> Any:
    """Replay every saved update through the aggregator
    (reference IterateAndUpdateImpl / StateTracker.updates())."""
    for worker_id in tracker.worker_updates():
        update = tracker.load_update(worker_id)
        if update is not None:
            aggregator.accumulate(Job(work=None, worker_id=worker_id,
                                      result=update))
    return aggregator.aggregate()
