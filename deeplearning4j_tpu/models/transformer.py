"""Causal transformer language model — beyond parity.

The reference (2014-era) predates transformers; this is the flagship
model family demonstrating the framework's pieces composing TPU-first:
the Pallas flash kernel for attention (128-aligned T and d_head >= 64
take the MXU path; other shapes fall back to blockwise automatically),
pre-LN residual blocks, one jitted + donated train step, whole-epoch
`lax.scan` training, and mesh-shardable parameters (every leaf carries
a leading- or trailing-dim structure the tp/dp shardings in
`parallel/` understand; see tests for a dp equivalence check).

Functional style (params pytree + pure apply) rather than the
MultiLayerNetwork builder: sequence models with weight tying and
per-block structure fit JAX's transform-first idiom, the same split the
LSTM module made (models/lstm.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.attention.flash_pallas import flash_attention


class TransformerConfig(NamedTuple):
    vocab_size: int
    d_model: int = 128
    n_heads: int = 2
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 256
    dtype: Any = jnp.float32
    #: interpret-mode pallas for CPU tests; ignored by the fallback
    interpret: bool = False


def init_transformer_params(key, cfg: TransformerConfig) -> Dict[str, Any]:
    """Embedding (tied with the output head), learned positions, and
    per-block {ln1, attn(Wq/Wk/Wv/Wo), ln2, ffn(W1/b1/W2/b2)}."""
    d, f = cfg.d_model, cfg.d_ff
    if d % cfg.n_heads:
        raise ValueError(f"d_model {d} not divisible by n_heads "
                         f"{cfg.n_heads}")
    keys = jax.random.split(key, 2 + 5 * cfg.n_layers)
    s = 0.02
    params: Dict[str, Any] = {
        "embed": s * jax.random.normal(keys[0], (cfg.vocab_size, d),
                                       cfg.dtype),
        "pos": s * jax.random.normal(keys[1], (cfg.max_len, d), cfg.dtype),
        "ln_f": {"g": jnp.ones((d,), cfg.dtype),
                 "b": jnp.zeros((d,), cfg.dtype)},
        "blocks": [],
    }
    for i in range(cfg.n_layers):
        k = keys[2 + 5 * i: 7 + 5 * i]
        params["blocks"].append({
            "ln1": {"g": jnp.ones((d,), cfg.dtype),
                    "b": jnp.zeros((d,), cfg.dtype)},
            "Wq": s * jax.random.normal(k[0], (d, d), cfg.dtype),
            "Wk": s * jax.random.normal(k[1], (d, d), cfg.dtype),
            "Wv": s * jax.random.normal(k[2], (d, d), cfg.dtype),
            "Wo": s * jax.random.normal(k[3], (d, d), cfg.dtype),
            "ln2": {"g": jnp.ones((d,), cfg.dtype),
                    "b": jnp.zeros((d,), cfg.dtype)},
            "W1": s * jax.random.normal(k[4], (d, f), cfg.dtype),
            "b1": jnp.zeros((f,), cfg.dtype),
            "W2": s * jax.random.normal(jax.random.fold_in(k[4], 1),
                                        (f, d), cfg.dtype),
            "b2": jnp.zeros((d,), cfg.dtype),
        })
    return params


def _layer_norm(p, x, eps=1e-5):
    # statistics in f32 even under bf16 params: bf16 mean/var over
    # d_model values is ~0.8%-noisy normalization every block
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return (((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
            * p["g"] + p["b"])


def _block(p, x, cfg: TransformerConfig):
    b, t, d = x.shape
    hd = d // cfg.n_heads
    h = _layer_norm(p["ln1"], x)

    def heads(w):
        return (h @ w).reshape(b, t, cfg.n_heads, hd).transpose(0, 2, 1, 3)

    # flash kernel over (B, H, T, hd); custom vjp supplies the backward
    att = flash_attention(heads(p["Wq"]), heads(p["Wk"]), heads(p["Wv"]),
                          True, interpret=cfg.interpret)
    att = att.transpose(0, 2, 1, 3).reshape(b, t, d)
    x = x + att @ p["Wo"]
    h = _layer_norm(p["ln2"], x)
    x = x + jax.nn.gelu(h @ p["W1"] + p["b1"]) @ p["W2"] + p["b2"]
    return x


def transformer_logits(params, tokens, cfg: TransformerConfig):
    """tokens: (B, T) int32 -> (B, T, vocab) logits. Output head tied
    to the embedding (standard weight tying)."""
    b, t = tokens.shape
    if t > cfg.max_len:
        raise ValueError(f"sequence {t} exceeds max_len {cfg.max_len}")
    x = params["embed"][tokens] + params["pos"][:t]
    for p in params["blocks"]:
        x = _block(p, x, cfg)
    x = _layer_norm(params["ln_f"], x)
    return x @ params["embed"].T


def lm_loss(params, tokens, cfg: TransformerConfig):
    """Next-token cross entropy, mean over (B, T-1) positions."""
    logits = transformer_logits(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return jnp.mean(nll)


def _sgd_momentum_update(params, velocity, grads, lr, momentum=0.9):
    """The one update rule both training entry points share."""
    velocity = jax.tree_util.tree_map(
        lambda v, g: momentum * v + g, velocity, grads)
    params = jax.tree_util.tree_map(
        lambda p, v: p - lr * v.astype(p.dtype), params, velocity)
    return params, velocity


def make_train_step(cfg: TransformerConfig, lr: float = 1e-2):
    """One jitted SGD+momentum step on the LM loss; params and momentum
    are donated (outputs alias their HBM)."""

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, velocity, tokens):
        loss, grads = jax.value_and_grad(lm_loss)(params, tokens, cfg)
        params, velocity = _sgd_momentum_update(params, velocity, grads,
                                                lr)
        return params, velocity, loss

    return step


def init_velocity(params):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, jnp.float32), params)


def fit_scan(params, tokens_batches, cfg: TransformerConfig,
             lr: float = 1e-2, epochs: int = 1):
    """Whole-epoch training as ONE compiled program (the fit_scan idiom:
    minibatches on a leading scan axis, zero per-step host dispatch).
    tokens_batches: (n_batches, B, T). Returns (params, last loss)."""

    @partial(jax.jit, donate_argnums=(0, 1), static_argnums=(3,))
    def run(params, velocity, batches, n_epochs):
        def one(carry, batch):
            params, velocity = carry
            loss, grads = jax.value_and_grad(lm_loss)(params, batch, cfg)
            params, velocity = _sgd_momentum_update(params, velocity,
                                                    grads, lr)
            return (params, velocity), loss

        def epoch(carry, _):
            carry, losses = jax.lax.scan(one, carry, batches)
            return carry, losses[-1]

        (params, velocity), last = jax.lax.scan(
            epoch, (params, velocity), None, length=n_epochs)
        return params, last[-1]

    return run(params, init_velocity(params), tokens_batches, int(epochs))


def generate(params, prompt, cfg: TransformerConfig, n_tokens: int,
             cache: bool = False):
    """Greedy decoding: prompt (B, T0) -> (B, T0 + n_tokens).

    `cache=True` routes through the preallocated KV cache
    (serving/kv_cache.py): prefill once, then O(1) decode steps inside
    one compiled scan — the serving path, parity-tested against the
    naive form below. `cache=False` keeps the full-recompute demo form
    (every step re-runs the whole prefix)."""
    b, t0 = prompt.shape
    if t0 + n_tokens > cfg.max_len:
        raise ValueError("generation would exceed max_len")
    if cache:
        if n_tokens < 1:
            raise ValueError(f"n_tokens must be >= 1, got {n_tokens}")
        # deferred import: serving builds on this module
        from deeplearning4j_tpu.serving.kv_cache import generate_cached
        return generate_cached(params, jnp.asarray(prompt, jnp.int32),
                               cfg, int(n_tokens))
    buf = jnp.zeros((b, t0 + n_tokens), jnp.int32).at[:, :t0].set(prompt)

    def step(buf, i):
        logits = transformer_logits(params, buf[:, :cfg.max_len], cfg)
        # next token = argmax at position t0 + i - 1
        nxt = jnp.argmax(
            jax.lax.dynamic_index_in_dim(logits, t0 + i - 1, axis=1,
                                         keepdims=False), axis=-1)
        return buf.at[:, t0 + i].set(nxt.astype(jnp.int32)), None

    # full-recompute over fixed-shape buffer keeps shapes static; pad
    # positions beyond the frontier influence nothing (causal mask)
    buf, _ = jax.lax.scan(step, buf, jnp.arange(n_tokens))
    return buf


__all__ = ["TransformerConfig", "init_transformer_params",
           "transformer_logits", "lm_loss", "make_train_step",
           "init_velocity", "fit_scan", "generate"]
