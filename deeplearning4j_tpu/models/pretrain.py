"""Unsupervised feature detectors: RBM, denoising AutoEncoder, recursive AE.

Parity: reference core/models/featuredetectors/rbm/RBM.java (CD-k via Gibbs
sampling with the 4 visible x 4 hidden unit-type matrix: contrastiveDivergence
:105, gradient :114, sampleHiddenGivenVisible :240, gibbhVh :292, propUp :344,
propDown :389, freeEnergy :222), autoencoder/AutoEncoder.java (encode :62,
decode :79, gradient w/ binomial corruption :111), recursive/
RecursiveAutoEncoder.java (sequence-fold reconstruction), and
core/nn/layers/BasePretrainNetwork.java (getCorruptedInput :95,
applySparsity :64).

TPU-native design
-----------------
The reference hand-derives every gradient. Here each model exposes a single
scalar `pretrain_loss(params, x, rng)` and the solver differentiates it with
`jax.grad`, so the whole pretrain step fuses into one XLA program:

* RBM: CD-k is not the gradient of any true loss, so we use the standard
  surrogate-energy formulation: run the Gibbs chain OUTSIDE the gradient
  (stop_gradient on every sample), then take
  ``loss = mean_energy(v0, h0) - mean_energy(vk, hk)``.
  d(loss)/dW = -(v0^T h0 - vk^T hk)/B — exactly the reference's
  positive-minus-negative phase moments (RBM.java:169-186) for every
  unit-type combination, because the bilinear energy is shared.
* The Gibbs chain uses explicit PRNG keys (split per step); `k` is a config
  constant so the chain unrolls into the jitted program.
* Rectified hidden units use proper relu (the reference's
  `Transforms.max(pre, 1.0)` at RBM.java:365 clamps at 1.0 — an alpha-era
  bug we do not reproduce); gaussian means are `pre` (not the reference's
  accidental `2*pre+noise` at RBM.java:370-372).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers import (BaseLayer, apply_dropout,
                                          register_layer)
from deeplearning4j_tpu.ops.activations import apply_activation
from deeplearning4j_tpu.ops.losses import loss_fn


def binomial_corruption(rng: jax.Array, x: jnp.ndarray,
                        corruption_level: float) -> jnp.ndarray:
    """Zero-mask each element with prob `corruption_level`
    (reference BasePretrainNetwork.getCorruptedInput :95)."""
    keep = jax.random.bernoulli(rng, 1.0 - corruption_level, x.shape)
    return x * keep


class BasePretrainLayer(BaseLayer):
    """Shared machinery for {W, b(hidden), vb(visible)} energy/AE models
    (reference BasePretrainNetwork + PretrainParamInitializer)."""

    def param_shapes(self) -> Dict[str, tuple]:
        c = self.conf
        return {"W": (c.n_in, c.n_out), "b": (1, c.n_out), "vb": (1, c.n_in)}

    # Subclasses implement: pretrain_loss(params, x, rng) -> scalar
    def reconstruct(self, params, x):
        raise NotImplementedError

    def sparsity_penalty(self, hidden_mean):
        """Pull mean hidden activation toward conf.sparsity (the reference's
        applySparsity bias-gradient nudge, BasePretrainNetwork.java:64,
        recast as a differentiable penalty)."""
        c = self.conf
        if c.sparsity == 0.0:
            return 0.0
        return jnp.sum(jnp.square(jnp.mean(hidden_mean, axis=0) - c.sparsity))


@register_layer("rbm")
class RBM(BasePretrainLayer):
    """Restricted Boltzmann Machine with CD-k.

    Unit types (conf.visible_unit x conf.hidden_unit), mirroring
    RBM.java's VisibleUnit {BINARY, GAUSSIAN, SOFTMAX, LINEAR} and
    HiddenUnit {BINARY, GAUSSIAN, SOFTMAX, RECTIFIED}.
    """

    # ------------------------------------------------------------ propagation
    def prop_up(self, params, v):
        """Hidden mean given visible (reference propUp :344)."""
        pre = v @ params["W"] + params["b"]
        h = self.conf.hidden_unit
        if h == "binary":
            return jax.nn.sigmoid(pre)
        if h == "rectified":
            return jax.nn.relu(pre)
        if h == "gaussian":
            return pre
        if h == "softmax":
            return jax.nn.softmax(pre, axis=-1)
        raise ValueError(f"Unknown hidden unit {h!r}")

    def prop_down(self, params, h):
        """Visible mean given hidden (reference propDown :389)."""
        pre = h @ params["W"].T + params["vb"]
        v = self.conf.visible_unit
        if v == "binary":
            return jax.nn.sigmoid(pre)
        if v in ("gaussian", "linear"):
            return pre
        if v == "softmax":
            return jax.nn.softmax(pre, axis=-1)
        raise ValueError(f"Unknown visible unit {v!r}")

    # --------------------------------------------------------------- sampling
    def sample_h_given_v(self, params, v, rng):
        """(mean, sample) of hidden given visible
        (reference sampleHiddenGivenVisible :240)."""
        mean = self.prop_up(params, v)
        h = self.conf.hidden_unit
        if h == "binary":
            sample = jax.random.bernoulli(rng, mean).astype(mean.dtype)
        elif h == "rectified":
            # NReLU: mean + N(0, sigmoid(mean)) clipped at 0
            noise = jax.random.normal(rng, mean.shape, mean.dtype)
            sample = jax.nn.relu(
                mean + noise * jnp.sqrt(jax.nn.sigmoid(mean)))
        elif h == "gaussian":
            sample = mean + jax.random.normal(rng, mean.shape, mean.dtype)
        else:  # softmax: reference uses the probs as the "sample"
            sample = mean
        return mean, sample

    def sample_v_given_h(self, params, h, rng):
        """(mean, sample) of visible given hidden
        (reference sampleVisibleGivenHidden :309)."""
        mean = self.prop_down(params, h)
        v = self.conf.visible_unit
        if v == "binary":
            sample = jax.random.bernoulli(rng, mean).astype(mean.dtype)
        elif v in ("gaussian", "linear"):
            sample = mean + jax.random.normal(rng, mean.shape, mean.dtype)
        else:  # softmax
            sample = mean
        return mean, sample

    def gibbs_vhv(self, params, h, rng):
        """One h -> v -> h Gibbs step (reference gibbhVh :292)."""
        kv, kh = jax.random.split(rng)
        v_mean, v_sample = self.sample_v_given_h(params, h, kv)
        h_mean, h_sample = self.sample_h_given_v(params, v_sample, kh)
        return (v_mean, v_sample), (h_mean, h_sample)

    # ----------------------------------------------------------------- energy
    def free_energy(self, params, v):
        """-log sum_h exp(-E(v,h)) for binary hidden
        (reference freeEnergy :222)."""
        wx_b = v @ params["W"] + params["b"]
        v_term = jnp.sum(v * params["vb"], axis=-1)
        h_term = jnp.sum(jax.nn.softplus(wx_b), axis=-1)
        return -h_term - v_term

    def _mean_energy(self, params, v, h):
        """Bilinear energy <E(v,h)> whose parameter-gradient reproduces the
        CD moment statistics for every unit type."""
        e = (jnp.sum(v * params["vb"], axis=-1)
             + jnp.sum(h * params["b"], axis=-1)
             + jnp.sum((v @ params["W"]) * h, axis=-1))
        return -jnp.mean(e)

    # ------------------------------------------------------------------- loss
    def pretrain_loss(self, params, x, rng: jax.Array):
        """CD-k surrogate loss (reference gradient() :114). The chain is
        sampled with stop_gradient so jax.grad yields exactly
        (negative-phase - positive-phase) moments."""
        k = max(1, self.conf.k)
        k0, *keys = jax.random.split(rng, k + 1)
        h0_mean, h0_sample = self.sample_h_given_v(params, x, k0)
        h = h0_sample
        v_sample = x
        for key in keys:  # k static -> unrolls into the XLA program
            (_, v_sample), (h_mean, h) = self.gibbs_vhv(params, h, key)
        sg = lax.stop_gradient
        pos = self._mean_energy(params, x, sg(h0_mean))
        neg = self._mean_energy(params, sg(v_sample), sg(h_mean))
        loss = pos - neg
        if self.conf.sparsity != 0.0:
            loss = loss + self.sparsity_penalty(self.prop_up(params, x))
        return loss

    # -------------------------------------------------------------- inference
    def reconstruct(self, params, x):
        """propUp then propDown (reference transform :433)."""
        return self.prop_down(params, self.prop_up(params, x))

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        """Forward activation inside a stacked net = hidden mean."""
        act = self.prop_up(params, x)
        return apply_dropout(rng, act, self.conf.dropout, training)


@register_layer("autoencoder")
class AutoEncoder(BasePretrainLayer):
    """Denoising autoencoder with tied weights
    (reference AutoEncoder.java: encode :62, decode :79, gradient :111)."""

    def encode(self, params, x):
        return apply_activation(self.conf.activation_function,
                                x @ params["W"] + params["b"])

    def decode(self, params, y):
        return apply_activation(self.conf.activation_function,
                                y @ params["W"].T + params["vb"])

    def reconstruct(self, params, x):
        return self.decode(params, self.encode(params, x))

    def pretrain_loss(self, params, x, rng: jax.Array):
        """Reconstruction loss of the corrupted input against the clean
        input, via the configured loss function (reference gradient :111
        hand-derives this for sigmoid+xent; autodiff covers all losses)."""
        c = self.conf
        corrupted = (binomial_corruption(rng, x, c.corruption_level)
                     if c.corruption_level > 0 else x)
        y = self.encode(params, corrupted)
        z = self.decode(params, y)
        loss = loss_fn(c.loss_function)(x, z)
        return loss + self.sparsity_penalty(y)

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        act = self.encode(params, x)
        return apply_dropout(rng, act, self.conf.dropout, training)


@register_layer("recursive_autoencoder")
class RecursiveAutoEncoder(BaseLayer):
    """Recursive autoencoder folding a sequence of rows
    (reference recursive/RecursiveAutoEncoder.java).

    h_0 = x_0;  h_i = act([x_i ; h_{i-1}] @ W + c);
    y_i = act(h_i @ U + bU) reconstructs [x_i ; h_{i-1}].
    Loss = mean over steps of 0.5*||y_i - [x_i;h_{i-1}]||^2
    (reference scoreSnapShot). Implemented as a lax.scan over the
    sequence so the fold compiles to one XLA while-like program instead
    of the reference's per-row Java loop.

    Param names follow RecursiveParamInitializer: W/c encoder, U/bU decoder.
    Hidden size == n_in so the recursion composes.
    """

    def param_shapes(self) -> Dict[str, tuple]:
        n = self.conf.n_in
        return {"W": (2 * n, n), "c": (1, n), "U": (n, 2 * n), "bU": (1, 2 * n)}

    def _encode(self, params, combined):
        return apply_activation(self.conf.activation_function,
                                combined @ params["W"] + params["c"])

    def _decode(self, params, hidden):
        return apply_activation(self.conf.activation_function,
                                hidden @ params["U"] + params["bU"])

    def _fold(self, params, x):
        """Scan the fold; x: (seq, n_in). Returns (final_hidden, total_loss)."""
        if x.shape[0] < 2:
            raise ValueError(
                "RecursiveAutoEncoder needs a sequence of >= 2 rows to fold; "
                f"got shape {x.shape}")

        def step(h_prev, x_i):
            combined = jnp.concatenate([x_i, h_prev], axis=-1)
            h = self._encode(params, combined[None, :])[0]
            y = self._decode(params, h[None, :])[0]
            loss = 0.5 * jnp.mean(jnp.square(y - combined))
            return h, (h, loss)

        h_final, (hs, losses) = lax.scan(step, x[0], x[1:])
        return h_final, jnp.mean(losses), hs

    def pretrain_loss(self, params, x, rng: Optional[jax.Array] = None):
        _, loss, _ = self._fold(params, x)
        return loss

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        """Hidden representation at every fold step: (seq-1, n_in)."""
        _, _, hs = self._fold(params, x)
        return hs
