"""Model families (RBM, autoencoders, LSTM, convolution) — importing this
package registers their layer types in the layer registry."""

from deeplearning4j_tpu.models.pretrain import (  # noqa: F401
    RBM,
    AutoEncoder,
    RecursiveAutoEncoder,
    binomial_corruption,
)
from deeplearning4j_tpu.models.conv import ConvolutionDownSampleLayer  # noqa: F401
from deeplearning4j_tpu.models.lstm import LSTM  # noqa: F401
from deeplearning4j_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    init_transformer_params,
    transformer_logits,
)
