"""Model families (RBM, autoencoders, LSTM, convolution) — importing this
package registers their layer types in the layer registry."""
