"""Model families (RBM, autoencoders, LSTM, convolution) — importing this
package registers their layer types in the layer registry."""

from deeplearning4j_tpu.models.pretrain import (  # noqa: F401
    RBM,
    AutoEncoder,
    RecursiveAutoEncoder,
    binomial_corruption,
)
