"""LSTM sequence model with beam-search decoding.

Parity: reference core/models/classifiers/lstm/LSTM.java — `activate` unrolled
IFOG-gate loop (:159-232), `lstmTick` single-step cell, `predict` + `BeamSearch`
(:234-330), params from LSTMParamInitializer
(core/nn/params/LSTMParamInitializer.java:33-46: "recurrentweights"
(1 + nIn + nHidden, 4*nHidden) with the bias folded in as the leading row,
"decoderweights" (nHidden, nOut), "decoderbias").

TPU-native design: the reference's per-timestep Java loop with row mutation
becomes a `lax.scan` over time — one compiled XLA while-loop whose body is a
single (1+d+d, 4d) matmul per step; manual BPTT (`backward` :81) is replaced
by jax.grad through the scan. Batched inputs (B, T, D) vmap the scan over B.
Gate layout matches the reference: [i | f | o] sigmoid, [g] tanh;
c_t = i*g + f*c_{t-1}; h_t = o * tanh(c_t) (o*c_t when activation != tanh).
Hidden size == n_in (LSTMParamInitializer.java:41 sets hiddenSize = nIn).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.nn.layers import (BaseLayer, apply_dropout,
                                          register_layer)
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import loss_fn


@register_layer("lstm")
class LSTM(BaseLayer):
    def _dims(self) -> Tuple[int, int]:
        d = self.conf.n_in  # hidden size == input size (reference parity)
        return d, self.conf.n_out

    def param_shapes(self) -> Dict[str, tuple]:
        d, n_out = self._dims()
        return {"R": (1 + 2 * d, 4 * d),  # [bias row; x_t; h_{t-1}] -> IFOG
                "Wd": (d, n_out),
                "bd": (1, n_out)}

    def init_params(self, key: jax.Array):
        c = self.conf
        shapes = self.param_shapes()
        k_r, k_d = jax.random.split(key)
        params = {
            "R": init_weights(k_r, shapes["R"], c.weight_init, c.dist,
                              jnp.dtype(c.dtype)),
            "Wd": init_weights(k_d, shapes["Wd"], c.weight_init, c.dist,
                               jnp.dtype(c.dtype)),
            "bd": jnp.zeros(shapes["bd"], jnp.dtype(c.dtype)),
        }
        for name in params:
            c.variable(name)
        return params

    # ---------------------------------------------------------------- cell
    def cell(self, params, x_t, h_prev, c_prev):
        """One LSTM tick (reference lstmTick): returns (h, c)."""
        d, _ = self._dims()
        cd = jnp.dtype(self.conf.compute_dtype)
        h_in = jnp.concatenate([jnp.ones_like(x_t[..., :1]), x_t, h_prev],
                               axis=-1)
        ifog = jnp.dot(h_in.astype(cd), params["R"].astype(cd),
                       preferred_element_type=jnp.float32
                       ).astype(x_t.dtype)
        gates = jax.nn.sigmoid(ifog[..., :3 * d])
        i, f, o = gates[..., :d], gates[..., d:2 * d], gates[..., 2 * d:3 * d]
        g = jnp.tanh(ifog[..., 3 * d:])
        c_new = i * g + f * c_prev
        if self.conf.activation_function == "tanh":
            h_new = o * jnp.tanh(c_new)
        else:
            h_new = o * c_new
        return h_new, c_new

    # ------------------------------------------------------------- forward
    def _scan_sequence(self, params, x, rng=None, training=False):
        """x: (T, n_in) -> hidden sequence (T, d) via lax.scan."""
        d, _ = self._dims()
        x = apply_dropout(rng, x, self.conf.dropout, training)

        def step(carry, x_t):
            h_prev, c_prev = carry
            h, c_new = self.cell(params, x_t, h_prev, c_prev)
            return (h, c_new), h

        zeros = jnp.zeros((d,), x.dtype)
        _, hs = lax.scan(step, (zeros, zeros), x)
        return hs

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        """Decoded outputs per timestep: (T, n_out) or (B, T, n_out)
        (reference activate :159 — which drops the first timestep; we emit
        all T so labels align 1:1 with inputs)."""
        if x.ndim == 3:
            if rng is not None:
                keys = jax.random.split(rng, x.shape[0])
                return jax.vmap(
                    lambda xi, ki: self.activate(params, xi, rng=ki,
                                                 training=training))(x, keys)
            return jax.vmap(
                lambda xi: self.activate(params, xi,
                                         training=training))(x)
        hs = self._scan_sequence(params, x, rng=rng, training=training)
        return hs @ params["Wd"] + params["bd"]

    def hidden_sequence(self, params, x):
        if x.ndim == 3:
            return jax.vmap(lambda xi: self._scan_sequence(params, xi))(x)
        return self._scan_sequence(params, x)

    def loss(self, params, x, labels, *, rng=None, training: bool = False,
             weights=None):
        """Sequence loss under the configured loss function; labels
        (T, n_out) or (B, T, n_out) align with activate(). `weights`
        (leading dim) masks device-feed padding rows — batched input
        only, where the leading dim is the example axis."""
        out = self.activate(params, x, rng=rng, training=training)
        if self.conf.loss_function in ("mcxent", "negativeloglikelihood"):
            out = jax.nn.softmax(out, axis=-1)
        return loss_fn(self.conf.loss_function)(labels, out, weights)

    # --------------------------------------------------------- streaming
    def _ensure_infer_jits(self) -> None:
        """Build the cached inference programs once per layer instance.
        params are TRACED arguments, so repeated predict()/run_stream()
        calls (and params updates between them) reuse one compiled
        program per input shape instead of re-tracing a fresh closure
        every call."""
        if getattr(self, "_tick_jit", None) is not None:
            return

        def tick(params, x_t, h, c):
            h_new, c_new = self.cell(params, x_t[None, :], h[None, :],
                                     c[None, :])
            y = h_new @ params["Wd"] + params["bd"]
            return y[0], h_new[0], c_new[0]

        def stream(params, x, h0, c0):
            def one(x_seq, h0, c0):
                def step(carry, x_t):
                    h, c = carry
                    h, c = self.cell(params, x_t, h, c)
                    return (h, c), h

                (h, c), hs = lax.scan(step, (h0, c0), x_seq)
                return hs, (h, c)

            if x.ndim == 3:
                hs, carry = jax.vmap(one)(x, h0, c0)
            else:
                hs, carry = one(x, h0, c0)
            return hs @ params["Wd"] + params["bd"], carry

        self._tick_jit = jax.jit(tick)
        self._stream_jit = jax.jit(stream)

    def run_stream(self, params, x, carry=None):
        """Decoded outputs AND the final recurrent state, as one
        compiled `lax.scan` step: x (T, n_in) or (B, T, n_in) ->
        (outputs matching activate(), (h, c) carry). Feed the returned
        carry back as `carry=` to continue a stream across calls —
        the chunked/streaming inference primitive (same cell math as
        activate, which always starts from zeros)."""
        d, _ = self._dims()
        x = jnp.asarray(x)
        if x.ndim not in (2, 3):
            raise ValueError(
                f"run_stream expects (T, n_in) or (B, T, n_in), got "
                f"shape {x.shape}")
        if carry is None:
            lead = x.shape[:-2]
            zeros = jnp.zeros((*lead, d), x.dtype)
            carry = (zeros, zeros)
        self._ensure_infer_jits()
        return self._stream_jit(params, x, carry[0], carry[1])

    # ---------------------------------------------------------- decoding
    def predict(self, params, x_init: jnp.ndarray, ws: jnp.ndarray,
                beam_size: int = 5, n_steps: int = 20,
                stop_token: int = 0) -> List[Tuple[List[int], float]]:
        """Beam-search decode (reference predict :234 + BeamSearch :256).

        `x_init`: (n_in,) start input; `ws`: (vocab, n_in) token embeddings.
        Returns [(token ids, log prob)] sorted best-first. The per-step
        cell is the cached compiled tick (params traced — one program
        across predict calls); the beam bookkeeping is host-side
        (data-dependent beam contents don't belong inside jit).
        """
        d, _ = self._dims()
        self._ensure_infer_jits()

        def tick(x_t, h, c):
            return self._tick_jit(params, x_t, h, c)

        zeros = jnp.zeros((d,), x_init.dtype)
        # Seed the beams from the model's prediction AFTER x_init: the first
        # tick's distribution picks the first tokens.
        y, h, c = tick(x_init, zeros, zeros)
        logprobs = np.asarray(jax.nn.log_softmax(y))
        top = np.argsort(-logprobs)[:beam_size]
        beams = [(float(logprobs[idx]), [int(idx)], h, c) for idx in top]
        for _ in range(n_steps - 1):
            candidates = []
            for logp, seq, h, c in beams:
                if seq[-1] == stop_token:
                    candidates.append((logp, seq, h, c))
                    continue
                y, h2, c2 = tick(ws[seq[-1]], h, c)
                logprobs = np.asarray(jax.nn.log_softmax(y))
                top = np.argsort(-logprobs)[:beam_size]
                for idx in top:
                    candidates.append((logp + float(logprobs[idx]),
                                       seq + [int(idx)], h2, c2))
            beams = heapq.nlargest(beam_size, candidates, key=lambda b: b[0])
            if all(b[1][-1] == stop_token for b in beams):
                break
        return [(seq, logp) for logp, seq, _, _ in
                sorted(beams, key=lambda b: -b[0])]
