"""Convolution + max-pool layer.

Parity: reference core/nn/layers/convolution/ConvolutionDownSampleLayer.java:52-88
(conv2d VALID -> maxPool(stride) -> broadcast per-feature-map bias ->
activation) with params named by ConvolutionParamInitializer
("convweights"/"convbias", core/nn/params/ConvolutionParamInitializer.java:33-44).

TPU-native design: NHWC layout with HWIO filters, the conv expressed as
patch-stack + one MXU dot (channels on lanes) and the max-pool as
crop/reshape/max — both chosen so forward AND backward lower to
slice/dot/select programs that the TPU toolchain compiles in seconds
(conv_general_dilated's and reduce_window's transposes each took minutes
here). Unlike the reference, whose `gradient()` returns null (conv
training was incomplete, ConvolutionDownSampleLayer.java:95), the layer
is fully trainable end-to-end via autodiff. The conv runs in
conf.compute_dtype (bfloat16 on the MXU when configured) accumulating in
float32.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers import (BaseLayer, apply_dropout,
                                          register_layer)
from deeplearning4j_tpu.ops.activations import apply_activation
from deeplearning4j_tpu.ops.initializers import init_weights


@register_layer("conv")
class ConvolutionDownSampleLayer(BaseLayer):
    """conv2d (VALID) + max-pool + bias + activation, NHWC.

    Config: `filter_size=[fh, fw]`, `num_in_feature_maps` (C_in),
    `num_feature_maps` (C_out), `stride=[sh, sw]` (pool window AND stride,
    matching the reference's Transforms.maxPool semantics).
    """

    def _filter_hw(self):
        fs = self.conf.filter_size or [2, 2]
        return int(fs[0]), int(fs[1])

    def _pool_hw(self):
        st = self.conf.stride or [2, 2]
        return int(st[0]), int(st[1])

    def param_shapes(self) -> Dict[str, tuple]:
        c = self.conf
        fh, fw = self._filter_hw()
        # HWIO filters ("convweights"); one bias per output feature map
        return {"W": (fh, fw, c.num_in_feature_maps, c.num_feature_maps),
                "b": (c.num_feature_maps,)}

    def init_params(self, key: jax.Array):
        c = self.conf
        shapes = self.param_shapes()
        params = {"b": jnp.zeros(shapes["b"], jnp.dtype(c.dtype)),
                  "W": init_weights(key, shapes["W"], c.weight_init, c.dist,
                                    jnp.dtype(c.dtype))}
        c.variable("W")
        c.variable("b")
        return params

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        c = self.conf
        fh, fw = self._filter_hw()
        if x.ndim != 4:
            raise ValueError(f"conv input must be NHWC, got shape {x.shape}")
        if x.shape[3] != c.num_in_feature_maps:
            # reference ConvolutionDownSampleLayer.activate:54 throws here too
            raise ValueError(
                f"Input feature maps {x.shape[3]} != configured "
                f"num_in_feature_maps {c.num_in_feature_maps}")
        if x.shape[1] < fh or x.shape[2] < fw:
            raise ValueError(
                f"Filter {fh}x{fw} larger than input {x.shape[1]}x{x.shape[2]}")
        cd = jnp.dtype(c.compute_dtype)
        # Stride-1 VALID conv as patch-stack + matmul: fh*fw shifted
        # slices concatenated on the channel axis, then one dot onto the
        # flattened HWIO filter. Identical math to conv_general_dilated
        # (slice order (dh*fw + dw)*C_in + ci matches the C-order filter
        # reshape), but lowers to slices + a single MXU dot whose
        # gradient is pad+add + two dots — conv_general_dilated's
        # backward takes minutes to compile on the TPU toolchain here,
        # vs seconds for this form. bf16 operands, f32 accumulation.
        xin = x.astype(cd)
        oh = x.shape[1] - fh + 1
        ow = x.shape[2] - fw + 1
        patches = jnp.concatenate(
            [xin[:, dh:dh + oh, dw:dw + ow, :]
             for dh in range(fh) for dw in range(fw)], axis=-1)
        w_flat = params["W"].astype(cd).reshape(-1, c.num_feature_maps)
        conv = jax.lax.dot_general(
            patches, w_flat, (((3,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.dtype(c.dtype))
        ph, pw = self._pool_hw()
        # window == stride (reference Transforms.maxPool semantics), so
        # pooling is a crop + reshape + max — equivalent to
        # reduce_window(VALID) but WITHOUT its select-and-scatter
        # gradient, whose TPU compile is pathological (~80 s per conv
        # layer vs ~2 s for the reshape formulation's compare/select)
        hh = conv.shape[1] // ph * ph
        ww = conv.shape[2] // pw * pw
        pooled = conv[:, :hh, :ww, :].reshape(
            conv.shape[0], hh // ph, ph, ww // pw, pw,
            conv.shape[3]).max(axis=(2, 4))
        act = apply_activation(c.activation_function, pooled + params["b"])
        return apply_dropout(rng, act, c.dropout, training)


register_layer("convolution")(ConvolutionDownSampleLayer)
