"""Async checkpoint writer: snapshot on the caller, serialize+IO off it.

The step loop's only synchronous cost is `snapshot_tree` — a device→host
copy of the state (per-shard D2H reads for mesh-sharded arrays, so each
device's slice ships once and lands in its own shard file). The copy is
double-buffering by construction: once the numpy snapshot exists the
live device buffers are free to keep updating (the fit loops donate them
to the next step), while a single background worker serializes the
snapshot to the sharded directory format and commits it.

In-flight saves are BOUNDED (`max_in_flight`): when the queue is full,
`save()` blocks until the worker drains a slot — backpressure, not
unbounded host-memory growth, when checkpoint cadence outruns disk.
Rotation (`keep`) garbage-collects old committed steps and any
uncommitted crash leftovers after every commit.

Telemetry (docs/OBSERVABILITY.md): `dl4j_ckpt_saves`,
`dl4j_ckpt_bytes_written`, `dl4j_ckpt_snapshot_seconds` (the step-loop
stall), `dl4j_ckpt_write_seconds` (worker-side serialize+IO),
`dl4j_ckpt_in_flight` gauge, `dl4j_ckpt_last_committed_step` gauge.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.checkpoint import format as ckfmt
from deeplearning4j_tpu.telemetry.trace import span

__all__ = ["snapshot_tree", "mesh_spec_of", "AsyncCheckpointWriter"]

_M_SAVES = telemetry.counter(
    "dl4j_ckpt_saves", "sharded checkpoint saves committed")
_M_BYTES = telemetry.counter(
    "dl4j_ckpt_bytes_written", "checkpoint shard bytes written")
_M_ERRORS = telemetry.counter(
    "dl4j_ckpt_errors", "checkpoint saves that failed")
_M_SNAP_S = telemetry.histogram(
    "dl4j_ckpt_snapshot_seconds",
    "device->host snapshot duration (the synchronous step-loop stall)")
_M_WRITE_S = telemetry.histogram(
    "dl4j_ckpt_write_seconds",
    "background serialize+IO duration per checkpoint")
_M_IN_FLIGHT = telemetry.gauge(
    "dl4j_ckpt_in_flight", "checkpoint saves snapshot-taken but not yet "
    "committed")
_M_LAST_STEP = telemetry.gauge(
    "dl4j_ckpt_last_committed_step", "newest committed checkpoint step")


def _is_jax_array(obj) -> bool:
    mod = type(obj).__module__ or ""
    return mod.startswith(("jax", "jaxlib")) and hasattr(obj, "dtype")


def _copy_to_host(x) -> np.ndarray:
    # an OWNED copy, never a view: on CPU backends np.asarray(jax_array)
    # can be zero-copy, and the fit loops DONATE the live buffers to the
    # next step — a view would let the background writer read torn data
    return np.array(x, copy=True)


def _snapshot_leaf(arr) -> Any:
    """One leaf device→host: a mesh-sharded jax.Array becomes a
    HostLeaf with one HostShard per DISTINCT device slice (replicated
    copies collapse to one); anything else copies whole."""
    if not _is_jax_array(arr):
        return np.asarray(arr) if isinstance(arr, np.generic) else arr
    shards = getattr(arr, "addressable_shards", None)
    if not shards or not getattr(arr, "is_fully_addressable", True):
        # multihost arrays: each process sees only its slice — gather is
        # the caller's job (the ZeRO-1 save_fn does); here take the local
        # view to stay crash-safe rather than deadlock on a collective
        return _copy_to_host(arr)
    seen = set()
    host_shards = []
    for s in shards:
        index = tuple((sl.start, sl.stop) for sl in s.index)
        if index in seen:
            continue
        seen.add(index)
        host_shards.append(ckfmt.HostShard(index, _copy_to_host(s.data)))
    if len(host_shards) == 1:
        # fully replicated (or single-device): store the plain array
        return host_shards[0].data
    return ckfmt.HostLeaf(dtype=ckfmt._dtype_name(arr.dtype),
                          shape=tuple(arr.shape), shards=host_shards)


def snapshot_tree(payload):
    """Device→host snapshot of a checkpoint payload pytree (dicts,
    tuples, lists, NamedTuples, scalars pass through; array leaves
    become np arrays or per-device HostLeafs)."""
    if payload is None or isinstance(payload, (bool, int, float, str)):
        return payload
    if isinstance(payload, ckfmt.HostLeaf):
        return payload
    if isinstance(payload, (np.ndarray, np.generic)) \
            or _is_jax_array(payload):
        return _snapshot_leaf(payload)
    if hasattr(payload, "_fields"):  # NamedTuple
        return type(payload)(*(snapshot_tree(v) for v in payload))
    if isinstance(payload, dict):
        return {k: snapshot_tree(v) for k, v in payload.items()}
    if isinstance(payload, tuple):
        return tuple(snapshot_tree(v) for v in payload)
    if isinstance(payload, list):
        return [snapshot_tree(v) for v in payload]
    return payload  # codec raises with the leaf path if unsupported


def mesh_spec_of(mesh=None, strategy: Optional[str] = None
                 ) -> Optional[dict]:
    """JSON-able record of the SOURCE topology — informational: restore
    never needs it (the shard table is self-describing), but `checkpoint
    inspect` and debugging do."""
    spec: Dict[str, Any] = {}
    if mesh is not None:
        spec["axes"] = {name: int(size)
                        for name, size in zip(mesh.axis_names,
                                              mesh.devices.shape)}
        spec["n_devices"] = int(np.prod(mesh.devices.shape))
    if strategy:
        spec["strategy"] = strategy
    return spec or None


class AsyncCheckpointWriter:
    """Background sharded-checkpoint writer for one checkpoint root.

    `save()` = synchronous snapshot + bounded enqueue; a single daemon
    worker serializes, commits (marker rename), rotates old steps, and
    resolves the returned Future with the committed directory. A worker
    failure is (a) set on that save's Future and (b) re-raised from the
    NEXT save()/flush()/close() call so a fit loop cannot silently train
    past a dead checkpoint stream.
    """

    def __init__(self, root: str, *, keep: int = 3, max_in_flight: int = 2,
                 sync: bool = False):
        if max_in_flight < 1:
            raise ValueError(
                f"max_in_flight must be >= 1, got {max_in_flight}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.root = root
        self.keep = keep
        self.sync = sync
        os.makedirs(root, exist_ok=True)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_in_flight)
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._pending = 0  # snapshot taken, commit not yet resolved
        self._cond = threading.Condition()
        self._closed = False
        self._auto_step = None  # next auto step when save(step=None)
        #: test hook — called with each filename before it is written
        #: (crash-mid-save drills raise from it)
        self.between_files: Optional[Callable[[str], None]] = None
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="ckpt-writer")
        self._worker.start()

    # ------------------------------------------------------------------ api
    def save(self, payload, *, step: Optional[int] = None,
             mesh_spec: Optional[dict] = None,
             wait: bool = False) -> str:
        """Snapshot `payload` and schedule its write; returns the step
        directory the checkpoint will commit to. Blocks only for the
        snapshot (plus backpressure when `max_in_flight` saves are
        already pending). `wait=True` (or a writer built with sync=True)
        blocks until the commit is durable — the preemption-flush path,
        where the process is about to die and an un-flushed Future is
        worthless."""
        self._reraise()
        if self._closed:
            raise RuntimeError("AsyncCheckpointWriter is closed")
        if step is None:
            step = self._next_auto_step()
        t0 = time.perf_counter()
        with span("ckpt_snapshot", step=int(step)):
            host = snapshot_tree(payload)
        _M_SNAP_S.observe(time.perf_counter() - t0)
        fut: Future = Future()
        with self._cond:
            self._pending += 1
        _M_IN_FLIGHT.inc()
        self._queue.put((int(step), host, mesh_spec, fut))
        if wait or self.sync:
            return fut.result()
        return os.path.join(self.root, ckfmt.step_dir_name(step))

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every enqueued save is committed (or failed)."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._pending == 0,
                                       timeout=timeout):
                raise TimeoutError(
                    f"checkpoint flush timed out after {timeout}s with "
                    f"{self._pending} saves pending")
        self._reraise()

    def close(self, timeout: Optional[float] = None) -> None:
        if self._closed:
            return
        self.flush(timeout)
        self._closed = True
        self._queue.put(None)  # wake + stop the worker
        self._worker.join(timeout=5.0)

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def in_flight(self) -> int:
        return self._pending

    def latest_step(self) -> Optional[int]:
        return ckfmt.latest_step(self.root)

    # ------------------------------------------------------------- internals
    def _next_auto_step(self) -> int:
        if self._auto_step is None:
            latest = ckfmt.latest_step(self.root)
            self._auto_step = 0 if latest is None else latest + 1
        step = self._auto_step
        self._auto_step += 1
        return step

    def _reraise(self) -> None:
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                f"background checkpoint write failed: {err}") from err

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            step, host, mesh_spec, fut = item
            t0 = time.perf_counter()
            try:
                with span("ckpt_write", step=step):
                    path = ckfmt.write_checkpoint(
                        self.root, step, host, mesh_spec=mesh_spec,
                        between_files=self.between_files)
                manifest = ckfmt.read_manifest(self.root, step)
                _M_BYTES.inc(manifest.get("total_bytes", 0))
                _M_SAVES.inc()
                _M_LAST_STEP.set(step)
                ckfmt.prune(self.root, self.keep, protect=(step,))
                fut.set_result(path)
            except BaseException as e:  # noqa: BLE001 — relay, don't die
                _M_ERRORS.inc()
                with self._error_lock:
                    self._error = e
                fut.set_exception(e)
            finally:
                _M_IN_FLIGHT.dec()
                _M_WRITE_S.observe(time.perf_counter() - t0)
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()
