"""Sharded checkpoint format: manifest + per-shard .npy files + commit marker.

Layout of one checkpoint root (one directory per run):

    <root>/
      step_0000000008/
        manifest.json          # pytree, per-leaf layout, mesh, cursor
        params__0__W.s00.npy   # one file per (leaf, shard)
        ...
        COMMITTED              # atomic marker — written LAST via os.replace

A reader only ever considers step directories carrying the ``COMMITTED``
marker, and the marker is published with an atomic rename, so a crash at
ANY point mid-save leaves the previous committed checkpoint as the
restore target — never a torn one. (This is the directory-format twin of
the reference's timestamp-rename discipline, DefaultModelSaver.java:66-70,
upgraded for multi-file payloads.)

The manifest records, per array leaf: logical dtype, GLOBAL shape, and a
shard table of (file, index, crc32) entries where ``index`` is a per-dim
[start, stop] slice ([null, null] = the full dim). A leaf saved from a
replicated array has one full-index shard; a leaf saved from a
mesh-sharded ``jax.Array`` has one shard per distinct device slice —
each device's bytes land in their own file, which is what makes the
format topology-portable: restore reassembles the global array from the
shard table and re-slices it for the TARGET sharding (the redistribution
problem of arXiv:2112.01075, solved here at the host layer).

Nothing is unpickled on load (``allow_pickle=False``) — same safety
contract as scaleout/checkpoint.py. Extension dtypes (bfloat16) are
round-tripped by recording the logical dtype in the manifest and
byte-viewing on load (numpy serializes them as raw void bytes).
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import numpy as np

from deeplearning4j_tpu.testing import chaos

__all__ = [
    "FORMAT_NAME", "FORMAT_VERSION", "MANIFEST", "MARKER", "HostShard",
    "HostLeaf", "CheckpointError", "CorruptShardError", "step_dir_name",
    "step_of", "list_steps", "latest_step", "write_checkpoint",
    "read_manifest", "load_tree", "leaf_summary", "prune",
]

FORMAT_NAME = "dl4j-sharded-checkpoint"
FORMAT_VERSION = 1
MANIFEST = "manifest.json"
MARKER = "COMMITTED"
_STEP_PREFIX = "step_"
_STEP_WIDTH = 10


class CheckpointError(RuntimeError):
    """Malformed / unreadable sharded checkpoint."""


class CorruptShardError(CheckpointError):
    """A shard file failed its checksum or shape validation; the message
    names the leaf so the operator knows WHAT was lost, not just that
    a read failed."""


class HostShard(NamedTuple):
    """One device's slice of a leaf, on host. ``index`` is a tuple of
    (start, stop) pairs per dim; (None, None) means the full dim."""

    index: Tuple[Tuple[Optional[int], Optional[int]], ...]
    data: np.ndarray


class HostLeaf(NamedTuple):
    """A host-side snapshot of one array leaf: logical dtype + global
    shape plus the shards that tile it (a single full-index shard for
    replicated/host arrays)."""

    dtype: str
    shape: Tuple[int, ...]
    shards: List[HostShard]

    @classmethod
    def from_array(cls, arr: np.ndarray) -> "HostLeaf":
        arr = np.asarray(arr)
        full = tuple((None, None) for _ in arr.shape)
        return cls(dtype=_dtype_name(arr.dtype), shape=tuple(arr.shape),
                   shards=[HostShard(full, arr)])


def _dtype_name(dt) -> str:
    """Stable dtype token: numpy's canonical name ('float32',
    'bfloat16', ...) — resolvable by np.dtype() because ml_dtypes
    registers the extension names."""
    return np.dtype(dt).name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bf16/f8 names with numpy

        return np.dtype(getattr(ml_dtypes, name))


def step_dir_name(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):0{_STEP_WIDTH}d}"


def step_of(dirname: str) -> Optional[int]:
    base = os.path.basename(dirname.rstrip("/"))
    if not base.startswith(_STEP_PREFIX):
        return None
    try:
        return int(base[len(_STEP_PREFIX):])
    except ValueError:
        return None


def list_steps(root: str, committed_only: bool = True) -> List[int]:
    """Ascending step numbers under `root` (default: committed only)."""
    try:
        entries = os.listdir(root)
    except FileNotFoundError:
        return []
    steps = []
    for name in entries:
        step = step_of(name)
        if step is None:
            continue
        if committed_only and not os.path.exists(
                os.path.join(root, name, MARKER)):
            continue
        steps.append(step)
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    steps = list_steps(root)
    return steps[-1] if steps else None


# ---------------------------------------------------------------- tree codec
def _namedtuple_registry() -> Dict[str, type]:
    # one shared registry with the legacy npz format (UpdaterState,
    # GuardianState, anything user-registered) — imported lazily because
    # scaleout's package init reaches back through nn/optimize
    from deeplearning4j_tpu.scaleout import checkpoint as _legacy

    return _legacy._NAMEDTUPLES


def _sanitize(part: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in part)


def _encode_tree(obj, path: str, leaves: Dict[str, HostLeaf]):
    """Encode a pytree into a JSON-able manifest node, moving every array
    leaf (np.ndarray / jax.Array / HostLeaf) into `leaves` under a
    path-derived key — so errors and shard filenames name the leaf
    ('params/0/W'), not an opaque counter."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj
    if isinstance(obj, HostLeaf):
        key = _leaf_key(path, leaves)
        leaves[key] = obj
        return {"__leaf__": key}
    if isinstance(obj, (np.ndarray, np.generic)) or _is_jax_array(obj):
        arr = np.asarray(obj)
        if arr.dtype.hasobject:
            raise TypeError(
                f"Cannot checkpoint object-dtype array at {path!r}")
        key = _leaf_key(path, leaves)
        leaves[key] = HostLeaf.from_array(arr)
        return {"__leaf__": key}
    if hasattr(obj, "_fields"):  # NamedTuple
        name = type(obj).__name__
        if name not in _namedtuple_registry():
            raise TypeError(
                f"Unregistered NamedTuple in checkpoint at {path!r}: {name} "
                "(scaleout.checkpoint.register_namedtuple)")
        return {"__nt__": name,
                "fields": {f: _encode_tree(getattr(obj, f), f"{path}/{f}",
                                           leaves)
                           for f in obj._fields}}
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise TypeError(
                    f"Checkpoint dict keys must be str at {path!r}, got "
                    f"{k!r} ({type(k).__name__})")
        return {"__dict__": {k: _encode_tree(v, f"{path}/{k}", leaves)
                             for k, v in obj.items()}}
    if isinstance(obj, tuple):
        return {"__tuple__": [_encode_tree(v, f"{path}/{i}", leaves)
                              for i, v in enumerate(obj)]}
    if isinstance(obj, list):
        return {"__list__": [_encode_tree(v, f"{path}/{i}", leaves)
                             for i, v in enumerate(obj)]}
    raise TypeError(
        f"Cannot checkpoint object of type {type(obj)!r} at {path!r}")


def _leaf_key(path: str, leaves: Dict[str, HostLeaf]) -> str:
    key = path.strip("/") or "root"
    if key in leaves:  # paths are unique by construction; belt+braces
        i = 1
        while f"{key}.{i}" in leaves:
            i += 1
        key = f"{key}.{i}"
    return key


def _is_jax_array(obj) -> bool:
    mod = type(obj).__module__ or ""
    return mod.startswith(("jax", "jaxlib")) and hasattr(obj, "dtype")


def _decode_tree(node, arrays: Dict[str, np.ndarray]):
    if not isinstance(node, dict):
        return node
    if "__leaf__" in node:
        return arrays[node["__leaf__"]]
    if "__nt__" in node:
        cls = _namedtuple_registry().get(node["__nt__"])
        if cls is None:
            raise CheckpointError(
                f"Checkpoint contains unregistered NamedTuple "
                f"{node['__nt__']!r} — import the module that registers it "
                "before restoring")
        return cls(**{f: _decode_tree(v, arrays)
                      for f, v in node["fields"].items()})
    if "__dict__" in node:
        return {k: _decode_tree(v, arrays)
                for k, v in node["__dict__"].items()}
    if "__tuple__" in node:
        return tuple(_decode_tree(v, arrays) for v in node["__tuple__"])
    if "__list__" in node:
        return [_decode_tree(v, arrays) for v in node["__list__"]]
    raise CheckpointError(f"Malformed checkpoint manifest node: {node!r}")


# -------------------------------------------------------------------- write
def write_checkpoint(root: str, step: int, payload: Any, *,
                     mesh_spec: Optional[dict] = None,
                     between_files: Optional[Callable[[str], None]] = None,
                     ) -> str:
    """Serialize `payload` (a pytree whose array leaves are np/jax arrays
    or pre-sharded `HostLeaf`s) as the sharded directory format and
    COMMIT it. Returns the committed step directory.

    `between_files` is a test hook called with each filename just before
    it is written — crash-mid-save drills raise from it and assert the
    step never becomes visible to readers. The chaos layer generalizes
    it: the `checkpoint.write` / `checkpoint.rename` injection points
    (deeplearning4j_tpu.testing.chaos) fire at the same sites, so
    seeded IO-fault schedules drive the same crash-atomicity contract
    without hand-wiring a callback.
    """
    leaves: Dict[str, HostLeaf] = {}
    tree = _encode_tree(payload, "", leaves)
    step_dir = os.path.join(root, step_dir_name(step))
    if os.path.exists(step_dir):
        # re-saving an existing step (resumed run): tear the old one down
        # first. Readers fall back to an OLDER committed step during the
        # window — strictly better than ever exposing a torn directory.
        shutil.rmtree(step_dir)
    os.makedirs(step_dir)

    manifest_leaves: Dict[str, dict] = {}
    total_bytes = 0
    for key, leaf in leaves.items():
        fname_base = _sanitize(key.replace("/", "__"))
        shard_entries = []
        seen_indices = set()
        for i, shard in enumerate(leaf.shards):
            idx_key = tuple(shard.index)
            if idx_key in seen_indices:  # replicated copies: save once
                continue
            seen_indices.add(idx_key)
            fname = f"{fname_base}.s{i:02d}.npy"
            if between_files is not None:
                between_files(fname)
            chaos.hit("checkpoint.write", file=fname)
            # NOT ascontiguousarray: it silently promotes 0-d scalars to
            # 1-d; tobytes() already yields C-order bytes for the crc
            data = np.asarray(shard.data)
            crc = zlib.crc32(data.tobytes())
            tmp = os.path.join(step_dir, fname + ".tmp")
            with open(tmp, "wb") as f:
                np.save(f, data)
            os.replace(tmp, os.path.join(step_dir, fname))
            total_bytes += data.nbytes
            shard_entries.append({
                "file": fname,
                "index": [[s[0], s[1]] for s in shard.index],
                "crc32": crc,
            })
        manifest_leaves[key] = {
            "dtype": leaf.dtype,
            "shape": list(leaf.shape),
            "shards": shard_entries,
        }

    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "step": int(step),
        "saved_at": time.time(),
        "mesh": mesh_spec,
        "tree": tree,
        "leaves": manifest_leaves,
        "total_bytes": total_bytes,
    }
    if between_files is not None:
        between_files(MANIFEST)
    chaos.hit("checkpoint.rename", file=MANIFEST)
    with open(os.path.join(step_dir, MANIFEST + ".tmp"), "w") as f:
        json.dump(manifest, f)
    os.replace(os.path.join(step_dir, MANIFEST + ".tmp"),
               os.path.join(step_dir, MANIFEST))
    # the commit point: marker appears atomically, LAST
    if between_files is not None:
        between_files(MARKER)
    chaos.hit("checkpoint.rename", file=MARKER)
    with open(os.path.join(step_dir, MARKER + ".tmp"), "w") as f:
        json.dump({"step": int(step), "committed_at": time.time()}, f)
    os.replace(os.path.join(step_dir, MARKER + ".tmp"),
               os.path.join(step_dir, MARKER))
    return step_dir


# --------------------------------------------------------------------- read
def _resolve_step(root: str, step: Optional[int]) -> int:
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(
                f"no committed sharded checkpoint under {root!r}")
        return step
    step_dir = os.path.join(root, step_dir_name(step))
    if not os.path.exists(os.path.join(step_dir, MARKER)):
        raise FileNotFoundError(
            f"step {step} under {root!r} is missing or was never committed "
            f"(committed steps: {list_steps(root)})")
    return int(step)


def read_manifest(root: str, step: Optional[int] = None) -> dict:
    step = _resolve_step(root, step)
    path = os.path.join(root, step_dir_name(step), MANIFEST)
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT_NAME:
        raise CheckpointError(
            f"{path} is not a {FORMAT_NAME} manifest")
    return manifest


def _assemble_leaf(step_dir: str, key: str, entry: dict,
                   verify: bool) -> np.ndarray:
    dtype = _resolve_dtype(entry["dtype"])
    shape = tuple(entry["shape"])
    shards = entry["shards"]
    if not shards:
        raise CorruptShardError(f"leaf {key!r}: manifest lists no shards")

    def load_shard(sh) -> np.ndarray:
        path = os.path.join(step_dir, sh["file"])
        try:
            with open(path, "rb") as f:
                raw = np.load(f, allow_pickle=False)
        except (OSError, ValueError) as e:
            raise CorruptShardError(
                f"leaf {key!r}: shard {sh['file']} unreadable: {e}") from e
        if verify and zlib.crc32(raw.tobytes()) != sh["crc32"]:
            raise CorruptShardError(
                f"leaf {key!r}: shard {sh['file']} failed its crc32 check — "
                "the checkpoint is corrupt; restore an earlier step")
        if raw.dtype != dtype:  # extension dtypes round-trip as raw void
            raw = raw.view(dtype)
        return raw

    if len(shards) == 1 and all(s == [None, None]
                                for s in shards[0]["index"]):
        arr = load_shard(shards[0])
        if tuple(arr.shape) != shape:
            raise CorruptShardError(
                f"leaf {key!r}: shard {shards[0]['file']} has shape "
                f"{tuple(arr.shape)}, manifest says {shape}")
        return arr

    out = np.empty(shape, dtype)
    filled = 0
    for sh in shards:
        idx = tuple(slice(s[0], s[1]) for s in sh["index"])
        data = load_shard(sh)
        try:
            out[idx] = data
        except ValueError as e:
            raise CorruptShardError(
                f"leaf {key!r}: shard {sh['file']} (index {sh['index']}) "
                f"does not fit the global shape {shape}: {e}") from e
        filled += data.size
    if filled < int(np.prod(shape)):
        raise CorruptShardError(
            f"leaf {key!r}: shards cover {filled} of "
            f"{int(np.prod(shape))} elements — the shard table does not "
            "tile the global array")
    return out


def load_tree(root: str, step: Optional[int] = None, *,
              verify: bool = True) -> Tuple[Any, dict]:
    """Load a committed checkpoint: reassemble every leaf's GLOBAL array
    from its shards (crc-verified) and decode the pytree. Returns
    (payload, manifest)."""
    step = _resolve_step(root, step)
    manifest = read_manifest(root, step)
    step_dir = os.path.join(root, step_dir_name(step))
    arrays = {key: _assemble_leaf(step_dir, key, entry, verify)
              for key, entry in manifest["leaves"].items()}
    return _decode_tree(manifest["tree"], arrays), manifest


def tree_scalars(manifest: dict):
    """Decode the manifest's payload tree WITHOUT touching any shard
    file: array leaves come back as None, every scalar/string/container
    node intact. `checkpoint inspect` uses this so summarizing a
    multi-GB checkpoint stays O(manifest), not O(checkpoint bytes)."""
    arrays = {key: None for key in manifest.get("leaves", {})}
    return _decode_tree(manifest["tree"], arrays)


def leaf_summary(manifest: dict) -> List[dict]:
    """[{leaf, dtype, shape, shards, bytes}] — `checkpoint inspect`'s
    table rows."""
    out = []
    for key, entry in sorted(manifest.get("leaves", {}).items()):
        itemsize = _resolve_dtype(entry["dtype"]).itemsize
        n = int(np.prod(entry["shape"])) if entry["shape"] else 1
        out.append({"leaf": key, "dtype": entry["dtype"],
                    "shape": tuple(entry["shape"]),
                    "shards": len(entry["shards"]),
                    "bytes": n * itemsize})
    return out


# ----------------------------------------------------------------- rotation
def prune(root: str, keep: int, *, protect: Sequence[int] = ()) -> List[int]:
    """Delete committed steps beyond the newest `keep`, plus any
    UNCOMMITTED step directories (crash leftovers) not in `protect`.
    Returns the steps removed."""
    removed = []
    committed = list_steps(root)
    doomed = committed[:-keep] if keep > 0 else []
    for name in (os.listdir(root) if os.path.isdir(root) else []):
        step = step_of(name)
        if step is None or step in protect:
            continue
        path = os.path.join(root, name)
        uncommitted = not os.path.exists(os.path.join(path, MARKER))
        if uncommitted or step in doomed:
            shutil.rmtree(path, ignore_errors=True)
            removed.append(step)
    return sorted(removed)
