"""Cross-topology checkpoint restore.

A sharded checkpoint records GLOBAL logical arrays (as a shard table);
restoring onto a different topology is therefore two moves:

1. **Reassemble** — `format.load_tree` stitches each leaf's shards back
   into its global host array (crc-verified, coverage-checked, errors
   naming the leaf).
2. **Re-slice** — place each global array under the TARGET sharding:
   `jax.device_put(global, target_sharding)` lets the runtime slice and
   distribute per the new (mesh, PartitionSpec), which is the whole
   array-redistribution problem (arXiv:2112.01075) delegated to the
   layer that already solves it. A restore into a jitted trainer doesn't
   even need the explicit put — jit's `in_shardings` reshard committed
   arrays on first dispatch.

Strategy portability rides on the canonical state form (convert.py):
params tree + per-layer UpdaterState + cursor, so DP ↔ ZeRO-1 ↔ TP and
8 devices ↔ 1 device are all the same restore with a different target.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

from deeplearning4j_tpu.checkpoint import format as ckfmt

__all__ = ["resolve_root", "discover_latest", "list_committed_steps",
           "load_payload_tree", "restore_network", "restore_params_for",
           "validate_like"]


def list_committed_steps(root: str) -> List[int]:
    """Ascending COMMITTED steps under `root`, hardened against a
    concurrent writer's rotation/GC: a step directory (or its marker /
    manifest) deleted between the listdir and the per-entry checks is
    skipped, never raised. This is the deployment watcher's scan
    primitive — it runs every poll interval against a root that an
    `AsyncCheckpointWriter` is actively pruning, so every filesystem
    probe must tolerate the entry vanishing under it."""
    try:
        entries = os.listdir(root)
    except (FileNotFoundError, NotADirectoryError):
        return []
    steps = []
    for name in entries:
        step = ckfmt.step_of(name)
        if step is None:
            continue
        step_dir = os.path.join(root, name)
        try:
            committed = (os.path.exists(os.path.join(step_dir, ckfmt.MARKER))
                         and os.path.exists(
                             os.path.join(step_dir, ckfmt.MANIFEST)))
        except OSError:
            continue
        if committed:
            steps.append(step)
    return sorted(steps)


def resolve_root(path: str) -> Tuple[str, Optional[int]]:
    """Accept either a checkpoint ROOT (holding step_* dirs) or one
    step directory; return (root, pinned_step_or_None)."""
    if os.path.exists(os.path.join(path, ckfmt.MANIFEST)):
        step = ckfmt.step_of(path)
        if step is None:
            raise ckfmt.CheckpointError(
                f"{path} holds a manifest but is not named step_<n>")
        return os.path.dirname(os.path.abspath(path)), step
    return path, None


def discover_latest(root: str) -> Tuple[str, int]:
    """`--resume auto`: locate the newest COMMITTED step under a
    checkpoint root (or accept a single step dir) without the caller
    naming the step. Raises CheckpointError naming the candidate torn
    step dirs when the root holds only uncommitted saves — the operator
    must know the difference between "nothing to resume" and "saves
    exist but none ever committed"."""
    root, pinned = resolve_root(root)
    if pinned is not None:
        return root, pinned
    # Newest-first, re-verifying each candidate's manifest is still
    # readable: a concurrent writer's prune() can delete a step between
    # our listdir and the manifest read — fall back to the next-older
    # committed step instead of raising. A rotating writer can even
    # blank the WHOLE snapshot (the newest step uncommitted at listdir
    # time, every older candidate pruned before its manifest read), so
    # a lost race re-scans before it is allowed to mean "nothing ever
    # committed" — prune only runs AFTER a newer commit, so the rescan
    # is guaranteed to see that newer committed step.
    for _ in range(3):
        steps = list_committed_steps(root)
        for step in reversed(steps):
            try:
                ckfmt.read_manifest(root, step)
            except (ckfmt.CheckpointError, OSError, ValueError):
                continue
            return root, step
        if not steps and not ckfmt.list_steps(root, committed_only=False):
            break  # truly empty root — not a race
    torn = ckfmt.list_steps(root, committed_only=False)
    if torn:
        raise ckfmt.CheckpointError(
            f"no COMMITTED checkpoint under {root!r}; found "
            f"{len(torn)} uncommitted (torn) step dir(s): "
            f"{[ckfmt.step_dir_name(s) for s in torn]} — these saves "
            "never reached their commit marker (crashed mid-write) and "
            "cannot be restored; delete them or point --resume at an "
            "older root")
    raise ckfmt.CheckpointError(
        f"no sharded checkpoint steps under {root!r}")


def load_payload_tree(path: str, step: Optional[int] = None
                      ) -> Tuple[Any, dict]:
    """(payload, manifest) with every array leaf reassembled to its
    global host array."""
    root, pinned = resolve_root(path)
    return ckfmt.load_tree(root, step if step is not None else pinned)


def restore_network(path: str, step: Optional[int] = None):
    """Rebuild a MultiLayerNetwork (+ canonical updater state + cursor)
    from a sharded checkpoint. Returns (network, info) with the same
    info contract as scaleout.checkpoint.load_checkpoint, plus 'step'
    and 'mesh' (the SOURCE topology, informational)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    payload, manifest = load_payload_tree(path, step)
    if payload.get("conf_json") is None:
        raise ValueError(
            f"Checkpoint {path} step {manifest['step']} has no conf_json "
            "(params-only runtime checkpoint); rebuild the network from "
            "its config and install payload['params'] directly")
    params = payload["params"]
    if isinstance(params, dict):
        net = MultiLayerNetwork.from_config_json(payload["conf_json"])
        net._params = jax.tree_util.tree_map(jnp.asarray, params)
    else:
        # runtime-level packed vector (the elastic supervisor's wave
        # checkpoints): unflatten against the conf's layer shapes
        net = MultiLayerNetwork.from_config_json(
            payload["conf_json"], params=np.asarray(params).ravel())
    if payload.get("updater_state") is not None:
        net._updater_state = jax.tree_util.tree_map(
            jnp.asarray, payload["updater_state"])
    net._iteration_count = payload.get("iteration_count", 0)
    info = {
        "iterator_position": payload.get("iterator_position"),
        "metadata": payload.get("metadata", {}),
        "saved_at": payload.get("saved_at"),
        "step": manifest["step"],
        "mesh": manifest.get("mesh"),
    }
    return net, info


def restore_params_for(path: str, shardings, step: Optional[int] = None):
    """Restore just the params tree, placed under `shardings` — a single
    sharding applied to every leaf, or a pytree of shardings matching
    the params tree (the TP trainer's `_param_specs` output, through
    NamedSharding). This is the explicit resharding entry point; the
    trainers' jitted `in_shardings` make it optional for training."""
    import jax

    payload, _ = load_payload_tree(path, step)
    params = payload["params"]
    if isinstance(shardings, jax.sharding.Sharding):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, shardings), params)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, s), params, shardings)


def validate_like(restored, reference, *, context: str = "restore") -> None:
    """Per-leaf dtype/shape validation with the LEAF PATH in the error —
    the 'clear error naming the mismatched leaf' the issue demands,
    instead of an opaque tree-structure or GSPMD shape failure later."""
    import jax

    ref_paths = {_path_str(p): leaf for p, leaf in
                 jax.tree_util.tree_flatten_with_path(reference)[0]}
    got_paths = {_path_str(p): leaf for p, leaf in
                 jax.tree_util.tree_flatten_with_path(restored)[0]}
    missing = sorted(set(ref_paths) - set(got_paths))
    extra = sorted(set(got_paths) - set(ref_paths))
    if missing or extra:
        raise ValueError(
            f"{context}: checkpoint tree does not match the target — "
            f"missing leaves {missing[:4]}, unexpected leaves {extra[:4]}")
    for path, ref in ref_paths.items():
        got = got_paths[path]
        ref_shape = tuple(getattr(ref, "shape", ()))
        got_shape = tuple(getattr(got, "shape", ()))
        if ref_shape != got_shape:
            raise ValueError(
                f"{context}: leaf {path!r} has shape {got_shape} in the "
                f"checkpoint but the target expects {ref_shape}")
        ref_dt = getattr(ref, "dtype", None)
        got_dt = getattr(got, "dtype", None)
        if ref_dt is not None and got_dt is not None and ref_dt != got_dt:
            # same shapes but a different dtype would silently change
            # serving numerics AND retrace every compiled bucket program
            # on the live request path — refuse, naming the leaf
            raise ValueError(
                f"{context}: leaf {path!r} has dtype {got_dt} in the "
                f"checkpoint but the target expects {ref_dt}")


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)
