"""ShardedModelSaver: the ModelSaver face of the async sharded writer.

Drop-in for the `saver=` kwarg everywhere the training stack takes one
(`MultiLayerNetwork.fit`/`fit_scan`, the DP/ZeRO-1/TP trainers,
`TrainingGuard` autosave): same two-call surface as DefaultModelSaver
(`save(network, ...)` / `save_current(params, ...)`), but the payload
lands in the sharded directory format (checkpoint/format.py) through the
bounded async writer (checkpoint/writer.py) — the step loop pays only
the device→host snapshot, and every autosave cadence that used to stall
for the full serialize+write now overlaps it with training.

The checkpoint step number is the guard's `iterator_position` cursor
when one is passed (so `step_0000000008/` IS "after batch 8"), else an
auto-incrementing counter.

Preemption flushes (`metadata["save_kind"] == "preempt"`) are written
SYNCHRONOUSLY: the process is about to die, so `save()` only returns
once the marker rename landed.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from deeplearning4j_tpu.checkpoint import format as ckfmt
from deeplearning4j_tpu.checkpoint.writer import (AsyncCheckpointWriter,
                                                  mesh_spec_of)
from deeplearning4j_tpu.scaleout.checkpoint import ModelSaver

__all__ = ["ShardedModelSaver", "SHARDED_FORMAT_VERSION"]

#: format_version 3 = sharded directory (1 = pickle [dead], 2 = npz)
SHARDED_FORMAT_VERSION = 3


class ShardedModelSaver(ModelSaver):
    def __init__(self, directory: str, *, keep: int = 3,
                 max_in_flight: int = 2, sync: bool = False,
                 mesh=None, strategy: Optional[str] = None):
        self.directory = directory
        self.writer = AsyncCheckpointWriter(directory, keep=keep,
                                            max_in_flight=max_in_flight,
                                            sync=sync)
        self._mesh_spec = mesh_spec_of(mesh, strategy)

    # ----------------------------------------------------------- lifecycle
    def flush(self, timeout: Optional[float] = None) -> None:
        self.writer.flush(timeout)

    def close(self) -> None:
        self.writer.close()

    def __enter__(self) -> "ShardedModelSaver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def latest_step(self) -> Optional[int]:
        return self.writer.latest_step()

    # ---------------------------------------------------------------- save
    def _payload(self, *, conf_json, params, updater_state,
                 iteration_count, iterator_position, metadata
                 ) -> Dict[str, Any]:
        import time

        return {
            "format_version": SHARDED_FORMAT_VERSION,
            "conf_json": conf_json,
            "params": params,
            "updater_state": updater_state,
            "iteration_count": iteration_count,
            "iterator_position": iterator_position,
            "metadata": metadata or {},
            "saved_at": time.time(),
        }

    def _write(self, payload, *, step, wait) -> str:
        return self.writer.save(payload, step=step,
                                mesh_spec=self._mesh_spec, wait=wait)

    def save(self, network, *, iterator_position: Optional[int] = None,
             metadata: Optional[Dict[str, Any]] = None,
             step: Optional[int] = None, wait: bool = False) -> str:
        """Checkpoint a network (params TREE — not the packed vector —
        so per-leaf sharding survives into the shard table) + updater
        state + cursor. Returns the step directory (commit may still be
        in flight unless wait=True/preempt)."""
        meta = dict(metadata or {})
        wait = wait or meta.get("save_kind") == "preempt"
        if step is None and iterator_position is not None:
            step = int(iterator_position)
        payload = self._payload(
            conf_json=network.to_json(),
            params=network._params,
            updater_state=network._updater_state,
            iteration_count=network._iteration_count,
            iterator_position=iterator_position,
            metadata=meta)
        return self._write(payload, step=step, wait=wait)

    def save_current(self, params, *, conf_json: Optional[str] = None,
                     iterator_position: Optional[int] = None,
                     metadata: Optional[Dict[str, Any]] = None,
                     step: Optional[int] = None, wait: bool = False) -> str:
        """Checkpoint a bare parameter pytree/vector (runtime-level save
        path — DefaultModelSaver.save_current's sharded twin)."""
        meta = dict(metadata or {})
        wait = wait or meta.get("save_kind") == "preempt"
        if step is None and iterator_position is not None:
            step = int(iterator_position)
        payload = self._payload(
            conf_json=conf_json, params=params, updater_state=None,
            iteration_count=0, iterator_position=iterator_position,
            metadata=meta)
        return self._write(payload, step=step, wait=wait)

    # ------------------------------------------------------------- inspect
    def manifest(self, step: Optional[int] = None) -> dict:
        return ckfmt.read_manifest(self.directory, step)

    @property
    def path(self) -> str:
        """Historical attribute parity with DefaultModelSaver (tests and
        tools read `.path` for the artifact location)."""
        return self.directory


def is_sharded_checkpoint(path: str) -> bool:
    """True when `path` is a sharded checkpoint root (holds committed
    step dirs) or a single committed step directory."""
    if not os.path.isdir(path):
        return False
    if os.path.exists(os.path.join(path, ckfmt.MANIFEST)):
        return True
    return ckfmt.latest_step(path) is not None
