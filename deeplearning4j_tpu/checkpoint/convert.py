"""Canonical ↔ strategy-specific optimizer-state conversion.

Checkpoints store optimizer state in ONE canonical form — the per-layer
``{layer: UpdaterState(hist, velocity, iteration)}`` pytree mirroring
the parameter tree — regardless of which trainer produced it. The DP/TP
trainers already carry exactly that; the ZeRO-1 trainer
(parallel/sharded_update.py) carries FLAT replica-sharded vectors
instead, so its saves convert flat→tree here and its restores convert
tree→flat. Both directions are pure host reshapes (ravel/unravel over
the same sorted-key flatten order `ravel_pytree` uses) — no arithmetic,
so a ZeRO-1 checkpoint restores BIT-identically into a DP or TP or
single-device run and back (the cross-strategy portability the issue's
acceptance demands).

The flatten order gotcha is inherited from ShardedUpdateTrainer:
``ravel_pytree`` flattens string-keyed dicts in SORTED key order
('0', '1', '10', '11', '2', ...), so these helpers walk layers in that
same order — never numeric order — or slices land on the wrong layers
at 11+ layers.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.optimize.updater import UpdaterState

__all__ = ["layer_slices", "flat_to_updater_state", "updater_state_to_flat"]


def layer_slices(params: Dict[str, dict]) -> Dict[str, Tuple[int, int]]:
    """{layer_key: (offset, size)} of each layer's slice of the packed
    vector, in ravel_pytree's sorted-key flatten order."""
    out = {}
    offset = 0
    for key in sorted(params):
        flat_i, _ = ravel_pytree(params[key])
        out[key] = (offset, int(flat_i.size))
        offset += int(flat_i.size)
    return out


def _np_unravel(like_tree, vec: np.ndarray):
    """Unflatten `vec` into `like_tree`'s structure/shapes as NUMPY
    leaves — same leaf order as ravel_pytree (tree_flatten order), but
    without the device round-trip ravel_pytree's unravel closure pays
    (it always produces jnp arrays)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(like_tree)
    out, off = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(np.asarray(vec[off:off + n]).reshape(leaf.shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def flat_to_updater_state(hist, velocity, iteration,
                          params: Dict[str, dict]) -> Dict[str, dict]:
    """ZeRO-1 flat vectors → canonical per-layer UpdaterState tree.

    `hist`/`velocity` are the UNPADDED packed vectors (length ==
    total param count; longer inputs are treated as device-count
    padding and sliced off); `iteration` is the shared scalar — every
    layer's UpdaterState gets it (the trainers advance all layers in
    lockstep, so per-layer counters are identical by construction).

    Leaves come back as HOST (numpy) arrays: this runs on the save path
    (the trainers' autosave) where a device copy would be a wasted
    H2D+D2H round trip — restore-side consumers (jitted trainers,
    restore_network) convert on first use.
    """
    hist = np.asarray(hist)
    velocity = np.asarray(velocity)
    slices = layer_slices(params)
    total = sum(size for _, size in slices.values())
    if hist.size < total or velocity.size < total:
        raise ValueError(
            f"flat optimizer state has {min(hist.size, velocity.size)} "
            f"elements but the network packs {total} parameters — "
            "checkpoint does not match this architecture")
    it = np.asarray(np.asarray(iteration), np.int32)
    state = {}
    for key, (off, size) in slices.items():
        state[key] = UpdaterState(
            hist=_np_unravel(params[key], hist[off:off + size]),
            velocity=_np_unravel(params[key], velocity[off:off + size]),
            iteration=it)
    return state


def updater_state_to_flat(state: Dict[str, dict], params: Dict[str, dict]
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Canonical per-layer UpdaterState tree → ZeRO-1 flat vectors
    (UNPADDED — the trainer re-pads to its own mesh width). Returns
    (hist, velocity, iteration) host arrays."""
    hists, vels = [], []
    iteration = None
    for key in sorted(params):
        if key not in state:
            raise ValueError(
                f"updater state has no entry for layer {key!r} — "
                "checkpoint does not match this architecture")
        st = state[key]
        h, _ = ravel_pytree(st.hist)
        v, _ = ravel_pytree(st.velocity)
        p, _ = ravel_pytree(params[key])
        if h.size != p.size or v.size != p.size:
            raise ValueError(
                f"layer {key!r}: updater state packs {int(h.size)} "
                f"elements, params pack {int(p.size)} — mismatched "
                "architecture")
        hists.append(np.asarray(h, np.float32))
        vels.append(np.asarray(v, np.float32))
        if iteration is None:
            iteration = np.asarray(st.iteration, np.int32)
    return (np.concatenate(hists), np.concatenate(vels),
            np.asarray(iteration, np.int32))
