"""Distributed checkpoint subsystem: sharded async save, cross-topology
resharded restore, serving hot-reload.

Replaces the single-blob checkpoint story (reference ModelSavingActor's
`nn-model.bin`, DefaultModelSaver.java:34-70; our npz port in
scaleout/checkpoint.py — kept as the compatibility shim) with a
production-shaped subsystem:

- **format.py** — a checkpoint is a DIRECTORY per step: JSON manifest
  (pytree structure, per-leaf dtype/global-shape/shard table, source
  mesh, cursor), per-shard `.npy` files with crc32 checksums, and a
  `COMMITTED` marker published by atomic rename LAST — a crash mid-save
  can never corrupt the latest restorable checkpoint.
- **writer.py** — `AsyncCheckpointWriter`: the step loop pays only the
  device→host snapshot (per-device shard reads); serialize+IO run on a
  background worker with BOUNDED in-flight saves, step rotation/GC, and
  telemetry (save duration/bytes/in-flight).
- **restore.py / convert.py** — restore a checkpoint saved under ANY
  (mesh, strategy) onto any other: shards reassemble into global arrays
  and re-slice per the target sharding (the redistribution problem of
  arXiv:2112.01075), while optimizer state converts losslessly between
  the ZeRO-1 flat vectors (arXiv:2004.13336, parallel/sharded_update.py)
  and the canonical per-layer UpdaterState tree — DP ↔ ZeRO-1 ↔ TP,
  8 devices ↔ 1, bit-identical.
- **saver.py** — `ShardedModelSaver`, the ModelSaver face: drop-in for
  `saver=` on fit/fit_scan/the trainers/TrainingGuard autosave; serving
  hot-reload consumes the same directories (`ReplicaSet.load_checkpoint`
  + the HTTP `/reload` endpoint).

Format spec, async lifecycle, resharding matrix and the hot-reload
quickstart: docs/CHECKPOINTS.md.
"""

from deeplearning4j_tpu.checkpoint.format import (  # noqa: F401
    CheckpointError,
    CorruptShardError,
    latest_step,
    leaf_summary,
    list_steps,
    load_tree,
    prune,
    read_manifest,
    tree_scalars,
    write_checkpoint,
)
from deeplearning4j_tpu.checkpoint.writer import (  # noqa: F401
    AsyncCheckpointWriter,
    mesh_spec_of,
    snapshot_tree,
)
from deeplearning4j_tpu.checkpoint.convert import (  # noqa: F401
    flat_to_updater_state,
    layer_slices,
    updater_state_to_flat,
)
from deeplearning4j_tpu.checkpoint.restore import (  # noqa: F401
    discover_latest,
    list_committed_steps,
    load_payload_tree,
    restore_network,
    restore_params_for,
    validate_like,
)
from deeplearning4j_tpu.checkpoint.saver import (  # noqa: F401
    ShardedModelSaver,
    is_sharded_checkpoint,
)

__all__ = [
    "CheckpointError", "CorruptShardError", "write_checkpoint", "load_tree",
    "read_manifest", "list_steps", "latest_step", "leaf_summary", "prune",
    "tree_scalars",
    "AsyncCheckpointWriter", "snapshot_tree", "mesh_spec_of",
    "flat_to_updater_state", "updater_state_to_flat", "layer_slices",
    "restore_network", "restore_params_for", "load_payload_tree",
    "discover_latest", "list_committed_steps",
    "validate_like", "ShardedModelSaver", "is_sharded_checkpoint",
]
