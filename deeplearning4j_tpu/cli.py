"""CLI: train / test / predict / serve subcommands.

Parity: reference deeplearning4j-cli — args4j subcommands `Train`/`Test`/
`Predict` with --input/--model/--output flags (cli/subcommands/Train.java:31
— whose `exec()` is an EMPTY STUB :46; this implementation does what it
advertised) and the URI-scheme input dispatch of cli/api/flags/Input.java
(here: .csv vs .ckpt vs .npz by extension). `serve` is beyond-parity:
the online endpoint over serving/ (docs/SERVING.md).

Usage:
    python -m deeplearning4j_tpu.cli train   -i data.csv -m conf.json -o model.ckpt
    python -m deeplearning4j_tpu.cli test    -i data.csv -m model.ckpt
    python -m deeplearning4j_tpu.cli predict -i data.csv -m model.ckpt -o preds.csv
    python -m deeplearning4j_tpu.cli serve   -m model.ckpt --port 8000

Telemetry (docs/OBSERVABILITY.md): `serve` answers GET /metrics on its
own port; `--metrics-port N` (train and serve) additionally starts a
standalone Prometheus endpoint (0 = auto-assign, printed), and
`--trace PATH` records host spans and writes a Chrome-trace JSON on
exit.

Input CSV: one row per example, features then (for train/test) one-hot or
integer label in the last column(s) — controlled by --label-columns.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Tuple

import numpy as np


def _load_csv(path: str, label_columns: int,
              n_classes: Optional[int] = None
              ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    data = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    if label_columns <= 0:
        return data, None
    x = data[:, :-label_columns]
    y = data[:, -label_columns:]
    if label_columns == 1:  # integer class column -> one-hot
        labels = y.astype(int).ravel()
        # class count comes from the MODEL (n_out), not the data — a file
        # missing the top class must not shrink the label width
        classes = n_classes if n_classes else int(labels.max()) + 1
        if labels.max() >= classes:
            raise ValueError(
                f"label {labels.max()} out of range for model with "
                f"{classes} output classes")
        y = np.eye(classes, dtype=np.float32)[labels]
    return x, y


def _load_model(path: str):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import load_checkpoint

    if path.endswith(".json"):  # fresh net from conf JSON
        with open(path) as f:
            return MultiLayerNetwork.from_config_json(f.read())
    net, _ = load_checkpoint(path)
    return net


def _model_n_out(net) -> Optional[int]:
    try:
        return net.conf.confs[-1].n_out or None
    except (AttributeError, IndexError):
        return None


class _Telemetry:
    """Shared --metrics-port / --trace plumbing for the entrypoints:
    optional standalone /metrics endpoint for the run's lifetime, and a
    Chrome-trace dump on exit."""

    def __init__(self, args):
        self.metrics = None
        self.trace_path = getattr(args, "trace", None)
        port = getattr(args, "metrics_port", None)
        if port is not None:
            from deeplearning4j_tpu.telemetry.exposition import \
                start_metrics_server

            self.metrics = start_metrics_server(port=port)
        if self.trace_path:
            from deeplearning4j_tpu.telemetry import start_tracing

            start_tracing()

    def announce(self) -> dict:
        return ({"metrics": self.metrics.url + "/metrics"}
                if self.metrics is not None else {})

    def close(self) -> dict:
        out = {}
        if self.trace_path:
            from deeplearning4j_tpu.telemetry import save_chrome_trace

            if save_chrome_trace(self.trace_path):
                out["trace"] = self.trace_path
        if self.metrics is not None:
            self.metrics.close()
        return out


def cmd_train(args) -> int:
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

    tele = _Telemetry(args)
    if tele.metrics is not None:
        # announce BEFORE the fit: the auto-assigned port is useless if
        # it first appears after the endpoint is already shut down
        print(json.dumps(tele.announce()), flush=True)
    try:
        net = _load_model(args.model)
        x, y = _load_csv(args.input, args.label_columns, _model_n_out(net))
        if y is None:
            print("train requires labels (--label-columns >= 1)",
                  file=sys.stderr)
            return 2
        net.fit(x, y, epochs=args.epochs)
        DefaultModelSaver(args.output).save(net)
        score = float(net.score(x, y))
    finally:
        # a failing fit (divergence abort, preemption) is exactly the
        # run whose trace is wanted: flush it on the way out too
        closed = tele.close()
    # announce() is NOT repeated here: the metrics endpoint is already
    # closed, and a dead URL in the summary line would mislead parsers
    print(json.dumps({"saved": args.output, "score": score, **closed}))
    return 0


def cmd_test(args) -> int:
    from deeplearning4j_tpu.eval.evaluation import Evaluation

    net = _load_model(args.model)
    x, y = _load_csv(args.input, args.label_columns, _model_n_out(net))
    if y is None:
        print("test requires labels (--label-columns >= 1)", file=sys.stderr)
        return 2
    ev = Evaluation()
    ev.eval(y, np.asarray(net.output(x)))
    print(ev.stats())
    print(json.dumps({"f1": ev.f1(), "accuracy": ev.accuracy(),
                      "precision": ev.precision(), "recall": ev.recall()}))
    return 0


def cmd_predict(args) -> int:
    # default 0: predict input is normally features-only; pass
    # --label-columns 1 to reuse a labelled train/test CSV
    x, _ = _load_csv(args.input, args.label_columns)
    net = _load_model(args.model)
    n_in = net.conf.confs[0].n_in
    if n_in and x.shape[1] != n_in:
        print(f"input has {x.shape[1]} feature columns but the model "
              f"expects {n_in}; use --label-columns to drop trailing "
              f"label column(s)", file=sys.stderr)
        return 2
    preds = net.predict(x)
    if args.output:
        np.savetxt(args.output, preds, fmt="%d")
        print(json.dumps({"saved": args.output, "n": int(preds.shape[0])}))
    else:
        for p in preds:
            print(int(p))
    return 0


def cmd_serve(args) -> int:
    from deeplearning4j_tpu.serving.server import serve_network

    tele = _Telemetry(args)
    try:
        net = _load_model(args.model)
        n_in = net.conf.confs[0].n_in
        handle = serve_network(
            net, host=args.host, port=args.port, n_replicas=args.replicas,
            max_batch_size=args.max_batch_size,
            max_delay_ms=args.max_delay_ms,
            warmup_shape=(n_in,) if (args.warmup and n_in) else None)
    except BaseException:
        tele.close()
        raise
    print(json.dumps({"serving": handle.url,
                      "replicas": len(handle.replicas.engines),
                      "max_batch_size": args.max_batch_size,
                      "max_delay_ms": args.max_delay_ms,
                      "metrics": handle.url + "/metrics",
                      **tele.announce()}), flush=True)
    if args.smoke:  # start/stop sanity check (tests, deploy probes)
        handle.close()
        tele.close()
        return 0
    try:
        handle.http.thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        handle.close()
        tele.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="deeplearning4j_tpu",
        description="TPU-native deeplearning4j: train/test/predict")
    sub = parser.add_subparsers(dest="command", required=True)

    def common(p, output_required):
        p.add_argument("--input", "-i", required=True, help="input CSV")
        p.add_argument("--model", "-m", required=True,
                       help="conf .json (fresh net) or .ckpt checkpoint")
        p.add_argument("--label-columns", type=int, default=1,
                       help="trailing label columns (1 = integer class)")
        if output_required is not None:
            p.add_argument("--output", "-o", required=output_required,
                           help="output path")

    def telemetry_flags(p):
        p.add_argument("--metrics-port", type=int, default=None,
                       help="start a standalone Prometheus /metrics "
                            "endpoint on this port (0 = auto-assign)")
        p.add_argument("--trace", default=None, metavar="PATH",
                       help="record host spans; write Chrome-trace JSON "
                            "here on exit (docs/OBSERVABILITY.md)")

    p_train = sub.add_parser("train", help="fit a model and checkpoint it")
    common(p_train, True)
    p_train.add_argument("--epochs", type=int, default=1)
    telemetry_flags(p_train)
    p_train.set_defaults(fn=cmd_train)

    p_test = sub.add_parser("test", help="evaluate a model")
    common(p_test, None)
    p_test.set_defaults(fn=cmd_test)

    p_pred = sub.add_parser("predict", help="emit class predictions")
    common(p_pred, False)
    p_pred.set_defaults(fn=cmd_predict, label_columns=0)

    p_serve = sub.add_parser(
        "serve", help="serve a model over HTTP (docs/SERVING.md)")
    p_serve.add_argument("--model", "-m", required=True,
                         help="conf .json (fresh net) or .ckpt checkpoint")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=0,
                         help="0 = auto-assign (printed on start)")
    p_serve.add_argument("--replicas", type=int, default=None,
                         help="device replicas (default: all local)")
    p_serve.add_argument("--max-batch-size", type=int, default=64,
                         help="micro-batcher coalescing cap / top bucket")
    p_serve.add_argument("--max-delay-ms", type=float, default=2.0,
                         help="micro-batcher coalescing window")
    p_serve.add_argument("--no-warmup", dest="warmup",
                         action="store_false",
                         help="skip precompiling the bucket programs")
    p_serve.add_argument("--smoke", action="store_true",
                         help="start, print the address, shut down")
    telemetry_flags(p_serve)
    p_serve.set_defaults(fn=cmd_serve)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
