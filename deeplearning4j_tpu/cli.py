"""CLI: train / test / predict / serve subcommands.

Parity: reference deeplearning4j-cli — args4j subcommands `Train`/`Test`/
`Predict` with --input/--model/--output flags (cli/subcommands/Train.java:31
— whose `exec()` is an EMPTY STUB :46; this implementation does what it
advertised) and the URI-scheme input dispatch of cli/api/flags/Input.java
(here: .csv vs .ckpt vs .npz by extension). `serve` is beyond-parity:
the online endpoint over serving/ (docs/SERVING.md).

Usage:
    python -m deeplearning4j_tpu.cli train   -i data.csv -m conf.json -o model.ckpt
    python -m deeplearning4j_tpu.cli train   ... --checkpoint-dir ckpts/
    python -m deeplearning4j_tpu.cli test    -i data.csv -m model.ckpt
    python -m deeplearning4j_tpu.cli predict -i data.csv -m model.ckpt -o preds.csv
    python -m deeplearning4j_tpu.cli serve   -m model.ckpt --port 8000
    python -m deeplearning4j_tpu.cli fleet   -m model.ckpt --replicas 3 --port 8000
    python -m deeplearning4j_tpu.cli checkpoint inspect ckpts/

`-m` accepts a conf .json (fresh net), a single-file .ckpt, or a sharded
checkpoint DIRECTORY (docs/CHECKPOINTS.md) for train/test/predict/serve.

Telemetry (docs/OBSERVABILITY.md): `serve` answers GET /metrics on its
own port; `--metrics-port N` (train and serve) additionally starts a
standalone Prometheus endpoint (0 = auto-assign, printed), and
`--trace PATH` records host spans and writes a Chrome-trace JSON on
exit.

Input CSV: one row per example, features then (for train/test) one-hot or
integer label in the last column(s) — controlled by --label-columns.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Tuple

import numpy as np


def _load_csv(path: str, label_columns: int,
              n_classes: Optional[int] = None
              ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    data = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    if label_columns <= 0:
        return data, None
    x = data[:, :-label_columns]
    y = data[:, -label_columns:]
    if label_columns == 1:  # integer class column -> one-hot
        labels = y.astype(int).ravel()
        # class count comes from the MODEL (n_out), not the data — a file
        # missing the top class must not shrink the label width
        classes = n_classes if n_classes else int(labels.max()) + 1
        if labels.max() >= classes:
            raise ValueError(
                f"label {labels.max()} out of range for model with "
                f"{classes} output classes")
        y = np.eye(classes, dtype=np.float32)[labels]
    return x, y


def _load_model(path: str):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import load_checkpoint

    if path.endswith(".json") and not os.path.isdir(path):
        with open(path) as f:  # fresh net from conf JSON
            return MultiLayerNetwork.from_config_json(f.read())
    # load_checkpoint dispatches: npz file OR sharded checkpoint dir
    net, _ = load_checkpoint(path)
    return net


def _transformer_from_spec(spec: str):
    """(params, cfg) from a transformer SPEC: a JSON object (inline or
    a file path) of TransformerConfig overrides plus an optional
    "seed". Initialization is a pure function of (seed, config), so
    every process given the same SPEC holds bit-identical weights."""
    import jax

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, init_transformer_params)

    raw = spec
    if os.path.exists(spec):
        with open(spec) as f:
            raw = f.read()
    fields = json.loads(raw)
    if not isinstance(fields, dict):
        raise ValueError("transformer SPEC must be a JSON object")
    seed = int(fields.pop("seed", 0))
    cfg = TransformerConfig(**fields)
    params = init_transformer_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


def _transformer_engine(spec: str):
    """Build a /generate engine from a `--transformer SPEC`
    (_transformer_from_spec). The same-SPEC determinism is the property
    the fleet's stream failover leans on: a greedy decode resumed on a
    survivor continues exactly where the dead replica stopped
    (docs/FLEET.md "Stream failover")."""
    from deeplearning4j_tpu.serving import InferenceEngine

    params, cfg = _transformer_from_spec(spec)
    return InferenceEngine.for_transformer(params, cfg)


def _activate_compile_cache(spec: Optional[str],
                            anchor: Optional[str]) -> Optional[str]:
    """`--compile-cache DIR|auto|off`: open the persistent AOT program
    cache BEFORE any engine/trainer jit is constructed (docs/WARMUP.md).
    `auto` co-locates the cache with `anchor` (the checkpoint/model
    dir) when one exists; with no flag at all the process still
    inherits `DL4J_TPU_COMPILE_CACHE` from a spawning parent lazily.
    Returns the active cache dir (for the announce line) or None."""
    from deeplearning4j_tpu import compilecache

    if spec and spec != "off":
        if spec == "auto":
            if not anchor or not os.path.isdir(anchor):
                return compilecache.active_dir()
            spec = compilecache.default_dir_for_checkpoints(anchor)
        compilecache.activate(spec)
    return compilecache.active_dir()


def _model_n_out(net) -> Optional[int]:
    try:
        return net.conf.confs[-1].n_out or None
    except (AttributeError, IndexError):
        return None


class _Telemetry:
    """Shared --metrics-port / --trace plumbing for the entrypoints:
    optional standalone /metrics endpoint for the run's lifetime, and a
    Chrome-trace dump on exit."""

    def __init__(self, args):
        self.metrics = None
        self.trace_path = getattr(args, "trace", None)
        port = getattr(args, "metrics_port", None)
        if port is not None:
            from deeplearning4j_tpu.telemetry.exposition import \
                start_metrics_server

            self.metrics = start_metrics_server(port=port)
        if self.trace_path:
            from deeplearning4j_tpu.telemetry import start_tracing

            start_tracing()

    def announce(self) -> dict:
        return ({"metrics": self.metrics.url + "/metrics"}
                if self.metrics is not None else {})

    def close(self) -> dict:
        out = {}
        if self.trace_path:
            from deeplearning4j_tpu.telemetry import save_chrome_trace

            if save_chrome_trace(self.trace_path):
                out["trace"] = self.trace_path
        if self.metrics is not None:
            self.metrics.close()
        return out


def cmd_train(args) -> int:
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

    # before jit construction AND before the elastic supervisor builds
    # its WorkerSpawner (which exports the cache dir to every worker)
    if args.checkpoint_dir and getattr(args, "compile_cache", None) \
            == "auto":
        os.makedirs(args.checkpoint_dir, exist_ok=True)
    _activate_compile_cache(getattr(args, "compile_cache", None),
                            args.checkpoint_dir)
    if args.elastic:
        return _cmd_train_elastic(args)
    tele = _Telemetry(args)
    if tele.metrics is not None:
        # announce BEFORE the fit: the auto-assigned port is useless if
        # it first appears after the endpoint is already shut down
        print(json.dumps(tele.announce()), flush=True)
    try:
        if args.checkpoint_every is not None and not args.checkpoint_dir:
            # refusing beats a run the user believes is checkpointed
            print("--checkpoint-every needs --checkpoint-dir DIR "
                  "(where the autosaves go)", file=sys.stderr)
            return 2
        resume_info = None
        if args.resume:
            net, resume_info = _resume_network(args)
            if net is None:
                return 2
        else:
            net = _load_model(args.model)
        x, y = _load_csv(args.input, args.label_columns, _model_n_out(net))
        if y is None:
            print("train requires labels (--label-columns >= 1)",
                  file=sys.stderr)
            return 2
        saver = None
        if args.checkpoint_dir:
            # sharded async autosaves off the hot path (docs/CHECKPOINTS.md)
            from deeplearning4j_tpu.checkpoint import ShardedModelSaver

            saver = ShardedModelSaver(args.checkpoint_dir,
                                      keep=args.checkpoint_keep)
        try:
            every = (args.checkpoint_every or 1
                     if saver is not None else None)
            if resume_info is not None:
                _fit_resumed(net, x, y, args, saver, resume_info)
            elif args.batch_size:
                # iterator path: the checkpoint cursor counts these
                # mini-batches, which is what --resume fast-forwards to
                from deeplearning4j_tpu.datasets import ListDataSetIterator
                from deeplearning4j_tpu.datasets.api import DataSet

                net.fit(ListDataSetIterator(DataSet(x, y),
                                            args.batch_size),
                        epochs=args.epochs, saver=saver,
                        checkpoint_every=every)
            else:
                net.fit(x, y, epochs=args.epochs, saver=saver,
                        checkpoint_every=every)
        finally:
            if saver is not None:
                saver.close()  # every pending autosave is durable
        DefaultModelSaver(args.output).save(net)
        score = float(net.score(x, y))
    finally:
        # a failing fit (divergence abort, preemption) is exactly the
        # run whose trace is wanted: flush it on the way out too
        closed = tele.close()
    # announce() is NOT repeated here: the metrics endpoint is already
    # closed, and a dead URL in the summary line would mislead parsers
    summary = {"saved": args.output, "score": score, **closed}
    if resume_info is not None:
        summary["resumed_from"] = resume_info["step"]
    print(json.dumps(summary))
    return 0


def _resume_network(args):
    """`--resume auto` (or an explicit path): restore params + updater
    state + cursor from the newest COMMITTED step — no step dir named.
    `auto` on an EMPTY checkpoint dir starts fresh (the restart-wrapper
    semantic, matching the elastic supervisor); a dir holding only torn
    saves still errors, listing the candidate step dirs. Returns
    (net, info), (net, None) for a fresh `auto` start, or (None, None)
    after printing the error."""
    from deeplearning4j_tpu.checkpoint.format import CheckpointError
    from deeplearning4j_tpu.checkpoint.restore import (discover_latest,
                                                       restore_network)

    source = args.checkpoint_dir if args.resume == "auto" else args.resume
    if not source:
        print("--resume auto needs --checkpoint-dir DIR to discover "
              "the latest committed step from", file=sys.stderr)
        return None, None
    try:
        root, step = discover_latest(source)
        net, info = restore_network(root, step)
    except (CheckpointError, FileNotFoundError) as e:
        if args.resume == "auto" and "no sharded checkpoint steps" \
                in str(e):
            # nothing saved yet: auto means "resume IF any" — a restart
            # wrapper's first launch starts fresh
            print(json.dumps({"resuming": None,
                              "note": "no committed checkpoint yet; "
                                      "starting fresh"}), flush=True)
            return _load_model(args.model), None
        print(f"cannot resume: {e}", file=sys.stderr)
        return None, None
    print(json.dumps({"resuming": root, "step": step,
                      "iterator_position": info.get("iterator_position"),
                      "epoch": info.get("metadata", {}).get("epoch")}),
          flush=True)
    return net, info


def _fit_resumed(net, x, y, args, saver, info) -> None:
    """Continue a restored run: fast-forward the data stream to the
    checkpoint's within-epoch cursor and seed the guard's position so
    new autosaves extend — never collide with — the committed steps."""
    from deeplearning4j_tpu.datasets import ListDataSetIterator
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.device_feed import DeviceFeed

    position = int(info.get("iterator_position") or 0)
    meta = info.get("metadata", {}) or {}
    epoch = int(meta.get("epoch") or 0)
    epoch_batch = int(meta.get("epoch_batch") or 0)
    bs = args.batch_size or len(x)
    feed = DeviceFeed(ListDataSetIterator(DataSet(x, y), bs))
    feed.fast_forward(epoch_batch)
    remaining = max(1, args.epochs - epoch)
    net.fit(feed, epochs=remaining, saver=saver,
            checkpoint_every=(args.checkpoint_every or 1
                              if saver is not None else None),
            start_position=position, start_epoch=epoch,
            start_epoch_batch=epoch_batch)


def _cmd_train_elastic(args) -> int:
    """`train --elastic N`: the self-healing out-of-process path — a
    TrainingSupervisor over N spawned workers with failure detection,
    bounded respawn, straggler defense, and checkpoint-backed elastic
    resume (docs/FAULT_TOLERANCE.md)."""
    import tempfile

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.api import CollectionJobIterator
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.scaleout.registry import ConfigRegistry
    from deeplearning4j_tpu.scaleout.supervisor import (TrainingSupervisor,
                                                        WorkerSpawner)

    if args.resume == "auto" and not args.checkpoint_dir:
        # same refusal as the non-elastic path: silently starting a
        # fresh run would discard progress the user asked to keep
        print("--resume auto needs --checkpoint-dir DIR to discover "
              "the latest committed step from", file=sys.stderr)
        return 2
    tele = _Telemetry(args)
    if tele.metrics is not None:
        # announce BEFORE the run (cmd_train's contract): an
        # auto-assigned metrics port is useless once the run is over
        print(json.dumps(tele.announce()), flush=True)
    try:
        net = _load_model(args.model)
        conf_json = net.to_json()
        x, y = _load_csv(args.input, args.label_columns, _model_n_out(net))
        if y is None:
            print("train requires labels (--label-columns >= 1)",
                  file=sys.stderr)
            return 2
        bs = args.batch_size or getattr(net.conf, "batch_size", None) or 32
        batches = [DataSet(x[i:i + bs], y[i:i + bs])
                   for i in range(0, len(x), bs)]
        jobs = [b for _ in range(args.epochs) for b in batches]
        state_dir = getattr(args, "state_dir", None)
        work = (state_dir or args.checkpoint_dir
                or tempfile.mkdtemp(prefix="dl4j_elastic_"))
        registry_root = os.path.join(work, "_registry")
        # with a state dir the run name must be STABLE across control-
        # plane incarnations: surviving workers rendezvous on it to
        # reconnect, and the restarted supervisor re-registers it. A
        # pid-scoped name is only safe when nothing outlives this
        # process.
        run_name = ("cli-elastic" if state_dir
                    else f"cli-elastic-{os.getpid()}")
        sup = TrainingSupervisor(
            CollectionJobIterator(jobs), run_name=run_name,
            registry=ConfigRegistry(registry_root),
            performer_class=("deeplearning4j_tpu.scaleout.perform."
                             "NeuralNetWorkPerformer"),
            performer_conf={"conf_json": conf_json, "epochs": 1},
            n_workers=args.elastic, conf_json=conf_json,
            spawner=WorkerSpawner(registry_root, run_name),
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume,
            max_respawns=args.max_respawns,
            straggler_factor=args.straggler_factor,
            keep_checkpoints=args.checkpoint_keep,
            status_port=args.status_port,
            state_dir=state_dir)
        if sup.status_server is not None:
            print(json.dumps({"status": sup.status_server.address,
                              "workers": args.elastic}), flush=True)
        final = sup.run(timeout=args.run_timeout)
        trained = MultiLayerNetwork.from_config_json(
            conf_json, params=np.asarray(final))
        DefaultModelSaver(args.output).save(trained)
        score = float(trained.score(x, y))
        print(json.dumps({
            "saved": args.output, "score": score,
            "workers": args.elastic, "waves": sup.waves,
            "jobs": len(jobs), "folded": len(sup.folded_seqs),
            "respawns": sup.respawns_used,
            "evictions": {k: int(c.value)
                          for k, c in sup._m_evictions.items()
                          if c.value},
            "resumes": len(sup.resume_events),
            "incarnation": sup.incarnation,
            "adopted": sum(1 for e in sup.adoption_events
                           if e["kind"] in ("adopted", "stray")),
            **tele.close()}))
        return 0
    except BaseException:
        tele.close()
        raise


def cmd_test(args) -> int:
    from deeplearning4j_tpu.eval.evaluation import Evaluation

    net = _load_model(args.model)
    x, y = _load_csv(args.input, args.label_columns, _model_n_out(net))
    if y is None:
        print("test requires labels (--label-columns >= 1)", file=sys.stderr)
        return 2
    ev = Evaluation()
    ev.eval(y, np.asarray(net.output(x)))
    print(ev.stats())
    print(json.dumps({"f1": ev.f1(), "accuracy": ev.accuracy(),
                      "precision": ev.precision(), "recall": ev.recall()}))
    return 0


def cmd_predict(args) -> int:
    # default 0: predict input is normally features-only; pass
    # --label-columns 1 to reuse a labelled train/test CSV
    x, _ = _load_csv(args.input, args.label_columns)
    net = _load_model(args.model)
    n_in = net.conf.confs[0].n_in
    if n_in and x.shape[1] != n_in:
        print(f"input has {x.shape[1]} feature columns but the model "
              f"expects {n_in}; use --label-columns to drop trailing "
              f"label column(s)", file=sys.stderr)
        return 2
    preds = net.predict(x)
    if args.output:
        np.savetxt(args.output, preds, fmt="%d")
        print(json.dumps({"saved": args.output, "n": int(preds.shape[0])}))
    else:
        for p in preds:
            print(int(p))
    return 0


def cmd_serve(args) -> int:
    from deeplearning4j_tpu.serving.server import serve_network

    tele = _Telemetry(args)
    try:
        # activate BEFORE model/engine construction so every jit the
        # serving stack builds goes through the AOT store
        cache_dir = _activate_compile_cache(
            args.compile_cache,
            args.model if os.path.isdir(args.model) else None)
        net = _load_model(args.model)
        n_in = net.conf.confs[0].n_in
        # initial checkpoint identity for /readyz//stats: what this
        # server was LAUNCHED from (reloads overwrite it) — the fleet
        # journal and the deployment controller read it end to end
        ck = None
        if os.path.isdir(args.model):
            from deeplearning4j_tpu.checkpoint.restore import \
                discover_latest
            try:
                _, ck_step = discover_latest(args.model)
            except Exception:
                ck_step = None
            ck = {"path": os.path.abspath(args.model), "step": ck_step}
        elif not args.model.endswith(".json"):
            ck = {"path": os.path.abspath(args.model), "step": None}
        gen = (_transformer_engine(args.transformer)
               if args.transformer else None)
        draft_params = draft_cfg = None
        if getattr(args, "draft_model", None):
            draft_params, draft_cfg = _transformer_from_spec(
                args.draft_model)
        handle = serve_network(
            net, checkpoint=ck, generate_engine=gen,
            host=args.host, port=args.port, n_replicas=args.replicas,
            max_batch_size=args.max_batch_size,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            batch_share=args.batch_share,
            slots=args.slots, page_size=args.page_size,
            kv_pages=args.kv_pages,
            prefix_cache=args.prefix_cache,
            fleet_kv=args.fleet_kv,
            kv_ship_timeout=args.kv_ship_timeout,
            decode_kernel=args.decode_kernel,
            horizon=args.horizon,
            speculation=args.speculation,
            drafter=args.drafter,
            draft_params=draft_params, draft_cfg=draft_cfg,
            draft_window=args.draft_window,
            warmup_shape=(n_in,) if (args.warmup and n_in) else None,
            warmup_async=args.warmup_async,
            warmup_plan=args.warmup_plan,
            role=args.role, model_id=args.model_id)
    except BaseException:
        tele.close()
        raise
    # the announce line's "decode" object is the ONE self-describing
    # record of the decode configuration this process actually runs —
    # fleet spawner logs capture it, so a drill's replica config is
    # auditable without re-deriving defaults (top-level slots/
    # page_size/... stay for older log parsers)
    loop = gen.decode_loop if gen is not None else None
    print(json.dumps({"serving": handle.url,
                      "role": args.role,
                      "model_id": args.model_id,
                      "replicas": len(handle.replicas.engines),
                      "max_batch_size": args.max_batch_size,
                      "max_delay_ms": args.max_delay_ms,
                      "slots": args.slots,
                      "page_size": args.page_size,
                      "prefix_cache": args.prefix_cache,
                      "decode_kernel": args.decode_kernel,
                      "decode": {
                          "kernel": {
                              "requested": args.decode_kernel,
                              "selected": (loop.decode_kernel
                                           if loop is not None else None),
                          },
                          "prefix_cache": args.prefix_cache,
                          "fleet_kv": (loop.fleet_kv
                                       if loop is not None
                                       else args.fleet_kv),
                          "slots": args.slots,
                          "batch_share": args.batch_share,
                          "page_size": args.page_size,
                          "kv_pages": (loop.n_pages
                                       if loop is not None else None),
                          "horizon": args.horizon,
                          "speculation": {
                              "enabled": bool(args.speculation),
                              "k": args.speculation,
                              "drafter": (
                                  loop._drafter.kind
                                  if loop is not None
                                  and loop._drafter is not None
                                  else None),
                              "draft_window": (
                                  args.draft_window
                                  if args.drafter == "model"
                                  and args.speculation else None),
                          },
                      },
                      "compile_cache": cache_dir,
                      "warmup_plan": handle.warmup_plan_path,
                      "metrics": handle.url + "/metrics",
                      **tele.announce()}), flush=True)
    if args.smoke:  # start/stop sanity check (tests, deploy probes)
        handle.close()
        tele.close()
        return 0
    try:
        handle.http.thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        handle.close()
        tele.close()
    return 0


def _parse_roles(spec: str) -> dict:
    """`prefill=1,decode=2` -> {"prefill": 1, "decode": 2}."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, n = part.partition("=")
        name = name.strip()
        if name not in ("prefill", "decode", "unified"):
            raise ValueError(
                f"--roles: unknown role {name!r} (expected "
                "prefill/decode/unified)")
        out[name] = int(n or 1)
        if out[name] < 0:
            raise ValueError(f"--roles: {name} count must be >= 0")
    return out


def _parse_models(spec: str) -> dict:
    """`tiny=conf.json,big=ckpt/` -> {"tiny": "conf.json", ...}."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, path = part.partition("=")
        if not name.strip() or not path.strip():
            raise ValueError(
                f"--models: need NAME=PATH, got {part!r}")
        out[name.strip()] = path.strip()
    return out


def cmd_fleet(args) -> int:
    """`fleet`: spawn N local replica server processes (and/or attach
    running ones by URL) behind the router tier — health-based
    eviction/rejoin, least-loaded routing with retries, load shedding,
    rolling `POST /reload`, `POST /scale` (docs/FLEET.md). `--roles`
    and/or `--models` replace the flat --replicas spawn with
    per-(model, role) pools: each pool's replicas get the matching
    `--role`/`--model-id` serve flags and autoscale independently."""
    from deeplearning4j_tpu.serving.fleet import (Autoscaler, Fleet,
                                                  ReplicaSpawner)
    from deeplearning4j_tpu.serving.router import (ReplicaClient,
                                                   serve_fleet)

    try:
        roles = _parse_roles(args.roles) if args.roles else {}
        models = _parse_models(args.models) if args.models else {}
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    pooled = bool(roles or models)
    if pooled and not models and not args.model:
        print("fleet --roles needs -m MODEL (or --models)",
              file=sys.stderr)
        return 2
    if not pooled and not args.attach \
            and (not args.model or args.replicas < 1):
        print("fleet needs -m MODEL with --replicas >= 1, --roles/"
              "--models, and/or --attach URL", file=sys.stderr)
        return 2
    autoscaler = None
    if args.autoscale and not pooled:
        lo, _, hi = args.autoscale.partition(":")
        autoscaler = Autoscaler(min_replicas=int(lo),
                                max_replicas=int(hi or lo))
    # activate before the spawner snapshots its child environment: every
    # replica (initial, autoscaled, respawned) inherits the warm cache
    _activate_compile_cache(
        getattr(args, "compile_cache", None),
        args.model if args.model and os.path.isdir(args.model) else None)
    spawner = None
    if not pooled and args.model \
            and (args.replicas > 0 or autoscaler is not None):
        # the fleet's KV mode leads the spawned replicas' serve args so
        # an explicit --serve-arg from the operator still wins (later
        # argparse occurrence overrides)
        spawner = ReplicaSpawner(
            args.model,
            serve_args=["--fleet-kv", args.fleet_kv] + args.serve_arg)
    tele = _Telemetry(args)
    fleet = Fleet(spawner=spawner,
                  heartbeat_interval=args.heartbeat_interval,
                  heartbeat_timeout=args.heartbeat_timeout,
                  shed_high_water=args.shed_high_water,
                  batch_high_water=args.batch_high_water,
                  request_timeout=args.request_timeout,
                  retry_budget=args.retry_budget,
                  stream_resume_attempts=args.stream_resume_attempts,
                  breaker_threshold=args.breaker_threshold,
                  breaker_reset_s=args.breaker_reset,
                  autoscaler=autoscaler,
                  state_dir=args.state_dir,
                  initial_checkpoint=(args.model
                                      if args.model
                                      and not args.model.endswith(".json")
                                      else None))
    # a crash-restarted router re-adopted its journaled replicas in the
    # Fleet constructor: only spawn the CAPACITY GAP, never a duplicate
    # world next to the warm one
    handoff_exit = bool(args.state_dir) and not args.smoke
    handle = None
    try:
        attached = {r["url"] for r in
                    fleet.snapshot()["replicas"].values()}
        for url in args.attach:
            if ReplicaClient(url).url not in attached:
                fleet.attach(url)
        if pooled:
            # per-(model, role) pools: each gets its own spawner whose
            # serve_args bake in the matching --role/--model-id, its
            # own autoscaler bounds, and spawns only the gap the
            # re-adopted warm world leaves (matched by announced
            # identity — journal adoption works per pool too)
            model_pools = models or {"default": args.model}
            role_layout = roles or {"unified": args.replicas}
            reps = fleet.snapshot()["replicas"]
            for mname, mpath in model_pools.items():
                for rname, want in role_layout.items():
                    sargs = ["--fleet-kv", args.fleet_kv]
                    if rname != "unified":
                        sargs += ["--role", rname]
                    if models:
                        sargs += ["--model-id", mname]
                    sargs += args.serve_arg
                    pool_scaler = None
                    if args.autoscale:
                        lo, _, hi = args.autoscale.partition(":")
                        pool_scaler = Autoscaler(
                            min_replicas=int(lo),
                            max_replicas=int(hi or lo))
                    fleet.add_pool(
                        model_id=mname, role=rname,
                        spawner=ReplicaSpawner(mpath,
                                               serve_args=sargs),
                        autoscaler=pool_scaler)
                    have = sum(
                        1 for r in reps.values()
                        if r["state"] != "evicted"
                        and (r.get("role") or "unified") == rname
                        and (r.get("model_id") or "default") == mname)
                    if want > have:
                        fleet.spawn_pool(mname, rname, want - have)
        elif spawner is not None and args.replicas > 0:
            # --replicas counts LOCAL processes: only spawned members
            # (the adopted warm world) fill the quota — attached URLs
            # are additive, exactly as on a fresh start
            have = sum(1 for r in fleet.snapshot()["replicas"].values()
                       if r["spawned"] and r["state"] != "evicted")
            if args.replicas > have:
                fleet.spawn(args.replicas - have)
        handle = serve_fleet(fleet, host=args.host, port=args.port,
                             fleet_kv=args.fleet_kv)
        fleet.wait_ready(1, timeout=args.ready_timeout)
    except BaseException:
        if handle is not None:
            handle.close(stop_replicas=not handoff_exit,
                         handoff=handoff_exit)
        else:
            fleet.close(stop_replicas=not handoff_exit,
                        handoff=handoff_exit)
        tele.close()
        raise
    # snapshot() reads membership under the fleet lock — the monitor
    # thread may be autoscale-spawning concurrently
    print(json.dumps({"router": handle.url,
                      "replicas": fleet.state_counts(),
                      "roles": fleet.role_counts(),
                      "incarnation": fleet.incarnation,
                      "adopted": sum(1 for e in fleet.adoption_events
                                     if e["kind"] in ("adopted",
                                                      "attached")),
                      "endpoints": [rep["url"] for rep in
                                    fleet.snapshot()["replicas"]
                                    .values()],
                      "metrics": handle.url + "/metrics",
                      **tele.announce()}), flush=True)
    if args.smoke:
        handle.close(stop_replicas=True)
        tele.close()
        return 0
    try:
        handle.http.thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        # with a state dir, an exiting router HANDS OFF its warm
        # replicas for the next incarnation (SIGKILL would anyway —
        # this makes a graceful stop match); without one, stopping the
        # router is stopping the fleet
        handle.close(stop_replicas=not handoff_exit,
                     handoff=handoff_exit)
        tele.close()
    return 0


def cmd_watchdog(args) -> int:
    """`watchdog -- <subcommand ...>`: restart-under-backoff wrapper so
    the control plane itself is supervised (docs/FAULT_TOLERANCE.md
    "Who watches the watcher"). Runs `python -m deeplearning4j_tpu.cli
    <subcommand ...>` and, while it exits non-zero (crash, OOM-kill,
    SIGKILL), restarts it with exponential backoff up to
    `--max-restarts` times. Paired with `--state-dir` on the wrapped
    `train --elastic` / `fleet`, each restart re-adopts the previous
    incarnation's journaled children instead of respawning them.

    The child is NOT placed in its own session and NOT registered for
    the orphan sweep: the watchdog dying must never take the control
    plane (or transitively the whole run) down with it."""
    import signal
    import subprocess
    import time as _time

    rest = list(args.cmd)
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        print("watchdog needs a wrapped subcommand: "
              "watchdog [opts] -- train --elastic ... --state-dir DIR",
              file=sys.stderr)
        return 2
    if rest[0] == "watchdog":
        print("watchdog cannot wrap itself", file=sys.stderr)
        return 2
    restarts = 0
    child = None

    def forward(signum, _frame):
        # operator stop is for the WHOLE plane: forward and stop
        # restarting (a forwarded SIGTERM exits the child non-zero,
        # which must not trigger a respawn)
        if child is not None and child.poll() is None:
            child.send_signal(signum)
        raise KeyboardInterrupt

    # both stop signals forward: a process manager signalling only the
    # watchdog pid (no process-group fan-out like terminal Ctrl-C) must
    # still reach the child so it can run its graceful handoff close
    old_term = signal.signal(signal.SIGTERM, forward)
    old_int = signal.signal(signal.SIGINT, forward)
    try:
        while True:
            # the KeyboardInterrupt guard spans the WHOLE iteration —
            # forward() raises from arbitrary main-thread points
            # (mid-Popen, mid-print, mid-backoff), and every one of
            # them must take the same stop-grace-then-kill exit, never
            # an uncaught traceback that leaves the child unreaped
            try:
                child = subprocess.Popen(
                    [sys.executable, "-m", "deeplearning4j_tpu.cli"]
                    + rest)
                print(json.dumps({"watchdog_child": child.pid,
                                  "restarts": restarts}), flush=True)
                rc = child.wait()
                if rc == 0:
                    print(json.dumps({"watchdog_done": True,
                                      "restarts": restarts}),
                          flush=True)
                    return 0
                if restarts >= args.max_restarts:
                    print(json.dumps({"watchdog_gave_up": True,
                                      "rc": rc,
                                      "restarts": restarts}),
                          flush=True)
                    return rc if rc > 0 else 1
                backoff = min(args.backoff * (2 ** restarts),
                              args.backoff_max)
                restarts += 1
                print(json.dumps({"watchdog_restart": restarts,
                                  "rc": rc,
                                  "backoff_s": round(backoff, 3)}),
                      flush=True)
                _time.sleep(backoff)
            except KeyboardInterrupt:
                if child is not None and child.poll() is None:
                    try:
                        child.wait(timeout=args.stop_grace)
                    except subprocess.TimeoutExpired:
                        child.kill()
                        child.wait()
                return 130
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)


def cmd_checkpoint(args) -> int:
    """`checkpoint inspect <dir>`: print the sharded-checkpoint manifest
    — committed steps, source mesh/strategy, cursor, and the per-leaf
    layout (dtype/global shape/shards/bytes)."""
    from deeplearning4j_tpu.checkpoint import (leaf_summary, list_steps,
                                               read_manifest, tree_scalars)

    from deeplearning4j_tpu.checkpoint.restore import resolve_root

    if args.action != "inspect":  # argparse choices already guard this
        print(f"unknown checkpoint action {args.action!r}", file=sys.stderr)
        return 2
    root, pinned = resolve_root(args.dir)  # root OR one step dir
    steps = list_steps(root)
    if not steps:
        print(f"no committed sharded checkpoint under {args.dir!r}",
              file=sys.stderr)
        return 2
    step = args.step if args.step is not None else pinned
    manifest = read_manifest(root, step)
    # scalars only — inspect must stay O(manifest), never read shards
    payload = tree_scalars(manifest)
    leaves = leaf_summary(manifest)
    out = {
        "dir": root,
        "steps": steps,
        "step": manifest["step"],
        "saved_at": manifest.get("saved_at"),
        "mesh": manifest.get("mesh"),
        "format_version": payload.get("format_version"),
        "iterator_position": payload.get("iterator_position"),
        "iteration_count": payload.get("iteration_count"),
        "metadata": {k: v for k, v in payload.get("metadata", {}).items()
                     if isinstance(v, (str, int, float, bool, type(None)))},
        "total_bytes": manifest.get("total_bytes"),
        "n_leaves": len(leaves),
    }
    if args.json:
        out["leaves"] = [{**row, "shape": list(row["shape"])}
                         for row in leaves]
        print(json.dumps(out))
        return 0
    print(json.dumps(out, indent=2))
    print(f"{'leaf':40s} {'dtype':10s} {'shape':18s} {'shards':>6s} "
          f"{'bytes':>12s}")
    for row in leaves:
        print(f"{row['leaf']:40s} {row['dtype']:10s} "
              f"{str(row['shape']):18s} {row['shards']:>6d} "
              f"{row['bytes']:>12d}")
    return 0


def cmd_eval(args) -> int:
    """`eval`: one-shot held-out evaluation of a checkpoint — the same
    gate the deployment controller (`pipeline`) runs before promoting,
    printing the same metrics JSON shape as `test`
    (docs/PIPELINE.md)."""
    from deeplearning4j_tpu.eval.holdout import evaluate_checkpoint

    try:
        out = evaluate_checkpoint(args.model, args.data,
                                  label_columns=args.label_columns,
                                  step=args.step)
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        print(f"eval failed: {exc}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(out))
    else:
        print(json.dumps(out, indent=2))
    return 0


def cmd_batch(args) -> int:
    """`batch`: bulk generation through a router (or single replica) on
    the BATCH SLO tier — the offline lane's reference client
    (docs/SERVING.md "Priority tiers").

    Reads a JSONL prompt file (each line a bare token list, or an
    object {"prompt": [...], "max_tokens": N}), drives chunks of
    --batch-size rows through ``POST /generate`` with
    ``"priority": "batch"`` (plus the X-Priority header so routers
    shed/forward without parsing the body), and appends one result
    line per row to --output. Progress is crash-safe: rows are fsynced
    to the output BEFORE the cursor journal (StateFile) commits, so a
    killed client restarts exactly where it stopped — uncommitted tail
    rows are truncated and re-run, committed rows are never re-emitted
    (each input row lands in the output exactly once). A 503 shed is
    waited out via the tier-aware ``retry_after_ms`` the shed reply
    carries; slot preemptions never surface here at all — the router's
    durable-stream resume replays them losslessly, and the reply's
    `preempt_resumes` count is accumulated into the summary."""
    import hashlib
    import time as _time
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.serving.errors import (PRIORITY_HEADER,
                                                   TIER_BATCH)
    from deeplearning4j_tpu.utils.statefile import StateFile

    rows = []
    with open(args.input, "rb") as f:
        raw = f.read()
    for ln, line in enumerate(raw.decode().splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        if isinstance(obj, list):
            rows.append((obj, args.max_tokens))
        elif isinstance(obj, dict) and "prompt" in obj:
            rows.append((obj["prompt"],
                         int(obj.get("max_tokens", args.max_tokens))))
        else:
            print(f"{args.input}:{ln}: each line must be a token list "
                  "or an object with \"prompt\"", file=sys.stderr)
            return 2
    if not rows:
        print(f"{args.input}: no prompt rows", file=sys.stderr)
        return 2
    input_sha = hashlib.sha256(raw).hexdigest()

    journal_path = args.journal or (args.output + ".journal")
    journal = StateFile(journal_path)
    state = journal.read()
    cursor = 0
    sheds_total = 0
    preempts_total = 0
    if state is not None:
        if state.get("input_sha") != input_sha:
            print(f"journal {journal_path} was committed against a "
                  "DIFFERENT input file (sha mismatch); delete the "
                  "journal (and the output) to start over",
                  file=sys.stderr)
            return 2
        cursor = int(state.get("cursor", 0))
        sheds_total = int(state.get("sheds", 0))
        preempts_total = int(state.get("preempt_resumes", 0))
    resumed_at = cursor

    # reconcile the output against the committed cursor: rows past it
    # were appended but never committed (crash between the output
    # fsync and the journal write) — truncate so they re-run; fewer
    # rows than the cursor promises means the pair was tampered with,
    # and resuming would silently drop rows
    if os.path.exists(args.output):
        with open(args.output, "rb+") as out:
            data = out.read()
            ends = [i for i, b in enumerate(data) if b == 0x0A]
            if len(ends) < cursor:
                print(f"output {args.output} holds {len(ends)} rows "
                      f"but the journal committed {cursor}; refusing "
                      "to resume from an inconsistent pair",
                      file=sys.stderr)
                return 2
            out.truncate(ends[cursor - 1] + 1 if cursor else 0)
    elif cursor:
        print(f"journal committed {cursor} rows but output "
              f"{args.output} is missing; delete the journal to start "
              "over", file=sys.stderr)
        return 2

    url = args.url.rstrip("/")
    headers = {"Content-Type": "application/json",
               PRIORITY_HEADER: TIER_BATCH}
    start = _time.perf_counter()
    out_f = open(args.output, "ab")
    try:
        while cursor < len(rows):
            chunk = rows[cursor:cursor + args.batch_size]
            body = {"prompt": [r[0] for r in chunk],
                    "max_tokens": [r[1] for r in chunk],
                    "priority": TIER_BATCH}
            if args.eos_id is not None:
                body["eos_id"] = args.eos_id
            payload = json.dumps(body).encode()
            sheds = 0
            while True:
                req = urllib.request.Request(url + "/generate",
                                             data=payload,
                                             headers=headers)
                try:
                    with urllib.request.urlopen(
                            req, timeout=args.timeout) as r:
                        reply = json.loads(r.read())
                    break
                except urllib.error.HTTPError as e:
                    raw_err = e.read()
                    if e.code == 503 and sheds < args.max_shed_retries:
                        # the batch lane shed us (it sheds FIRST, at
                        # its own lower high-water mark): wait out the
                        # backlog-derived Retry-After and try again
                        sheds += 1
                        sheds_total += 1
                        try:
                            err = json.loads(raw_err)
                        except ValueError:
                            err = {}
                        wait = min(5.0, max(
                            0.05,
                            float(err.get("retry_after_ms", 1000))
                            / 1000.0))
                        _time.sleep(wait)
                        continue
                    print(f"batch: /generate answered {e.code}: "
                          f"{raw_err.decode(errors='replace')[:200]}",
                          file=sys.stderr)
                    return 3
            if "error" in reply:
                # a durable-stream router reports an exhausted resume
                # budget in-band, not as a raw 5xx
                print(f"batch: generation failed: {reply['error']}",
                      file=sys.stderr)
                return 3
            toks = reply["tokens"]
            reasons = (reply.get("finish_reasons")
                       or [None] * len(toks))
            preempts_total += int(reply.get("preempt_resumes", 0) or 0)
            for i in range(len(chunk)):
                out_f.write((json.dumps(
                    {"row": cursor + i,
                     "tokens": toks[i],
                     "finish_reason": reasons[i]}) + "\n").encode())
            # rows reach disk BEFORE the cursor commits: a crash
            # between the two re-runs the chunk (truncated on resume),
            # never skips or duplicates it
            out_f.flush()
            os.fsync(out_f.fileno())
            cursor += len(chunk)
            journal.write({"input": os.path.abspath(args.input),
                           "input_sha": input_sha,
                           "output": os.path.abspath(args.output),
                           "cursor": cursor,
                           "total": len(rows),
                           "sheds": sheds_total,
                           "preempt_resumes": preempts_total})
            if args.progress:
                print(json.dumps({"cursor": cursor,
                                  "total": len(rows),
                                  "sheds": sheds_total,
                                  "preempt_resumes": preempts_total}),
                      flush=True)
    finally:
        out_f.close()
    print(json.dumps({"batch_done": True,
                      "rows": len(rows),
                      "resumed_at": resumed_at,
                      "output": os.path.abspath(args.output),
                      "journal": journal_path,
                      "sheds": sheds_total,
                      "preempt_resumes": preempts_total,
                      "seconds": round(_time.perf_counter() - start,
                                       3)}), flush=True)
    return 0


def cmd_pipeline(args) -> int:
    """`pipeline`: the crash-safe train→serve deployment controller —
    watch --checkpoint-dir for newly COMMITTED steps, gate each on a
    held-out eval, canary-promote it through the fleet's rolling
    /reload, roll back + quarantine on failure (docs/PIPELINE.md).
    Journals to --state-dir/controller.journal so a killed controller
    (run it under `watchdog`) restarts into the same decision."""
    from deeplearning4j_tpu.deploy import (ControllerBusy,
                                           DeploymentController)

    if bool(args.fleet_url) == bool(args.spawn_fleet):
        print("pipeline needs exactly one of --fleet-url URL or "
              "--spawn-fleet (with -m MODEL)", file=sys.stderr)
        return 2
    if args.spawn_fleet and not args.model:
        print("--spawn-fleet needs -m MODEL for the replicas",
              file=sys.stderr)
        return 2
    if args.eval_via_fleet and not args.fleet_url:
        print("--eval-via-fleet scores the LIVE fleet over HTTP and "
              "needs --fleet-url (a router endpoint, not --spawn-fleet)",
              file=sys.stderr)
        return 2
    probe = None
    if args.probe:
        probe = json.loads(args.probe)
    # canary replicas the controller promotes should boot warm too:
    # activate here so the spawned fleet's child env carries the cache
    _activate_compile_cache(getattr(args, "compile_cache", None),
                            args.checkpoint_dir)
    tele = _Telemetry(args)
    fleet = None
    handle = None
    handoff_exit = bool(args.state_dir) and not args.smoke
    ctrl = None
    try:
        if args.spawn_fleet:
            from deeplearning4j_tpu.serving.fleet import (Fleet,
                                                          ReplicaSpawner)
            from deeplearning4j_tpu.serving.router import serve_fleet
            fleet = Fleet(
                spawner=ReplicaSpawner(args.model,
                                       serve_args=args.serve_arg),
                state_dir=(os.path.join(args.state_dir, "fleet")
                           if args.state_dir else None),
                initial_checkpoint=(args.model
                                    if not args.model.endswith(".json")
                                    else None))
            have = sum(1 for r in fleet.snapshot()["replicas"].values()
                       if r["spawned"] and r["state"] != "evicted")
            if args.replicas > have:
                fleet.spawn(args.replicas - have)
            handle = serve_fleet(fleet, host=args.host, port=args.port)
            fleet.wait_ready(1, timeout=args.ready_timeout)
        ctrl = DeploymentController(
            args.checkpoint_dir,
            fleet=fleet,
            fleet_url=args.fleet_url,
            eval_data=args.eval_data,
            eval_via_fleet=args.eval_via_fleet,
            label_columns=args.label_columns,
            metric=args.metric,
            eval_threshold=args.eval_threshold,
            regression_margin=args.regression_margin,
            poll_interval=args.poll_interval,
            probe=probe,
            state_dir=args.state_dir,
            name=args.name,
            status_port=args.status_port)
    except ControllerBusy as exc:
        print(f"pipeline already running: {exc}", file=sys.stderr)
        if handle is not None:
            handle.close(stop_replicas=not handoff_exit,
                         handoff=handoff_exit)
        elif fleet is not None:
            fleet.close(stop_replicas=not handoff_exit,
                        handoff=handoff_exit)
        tele.close()
        return 3
    except BaseException:
        if handle is not None:
            handle.close(stop_replicas=not handoff_exit,
                         handoff=handoff_exit)
        elif fleet is not None:
            fleet.close(stop_replicas=not handoff_exit,
                        handoff=handoff_exit)
        tele.close()
        raise
    print(json.dumps({"pipeline": ctrl.name,
                      "checkpoint_dir": os.path.abspath(
                          args.checkpoint_dir),
                      "fleet": (handle.url if handle is not None
                                else args.fleet_url),
                      "status": ctrl.status_address,
                      "incarnation": ctrl.incarnation,
                      **tele.announce()}), flush=True)
    try:
        if args.smoke:
            return 0
        ctrl.run(max_cycles=args.cycles)
    except KeyboardInterrupt:
        pass
    finally:
        ctrl.close(release=True)
        if handle is not None:
            handle.close(stop_replicas=not handoff_exit,
                         handoff=handoff_exit)
        tele.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="deeplearning4j_tpu",
        description="TPU-native deeplearning4j: train/test/predict")
    sub = parser.add_subparsers(dest="command", required=True)

    def common(p, output_required):
        p.add_argument("--input", "-i", required=True, help="input CSV")
        p.add_argument("--model", "-m", required=True,
                       help="conf .json (fresh net), .ckpt checkpoint, or "
                            "sharded checkpoint dir")
        p.add_argument("--label-columns", type=int, default=1,
                       help="trailing label columns (1 = integer class)")
        if output_required is not None:
            p.add_argument("--output", "-o", required=output_required,
                           help="output path")

    def telemetry_flags(p):
        p.add_argument("--metrics-port", type=int, default=None,
                       help="start a standalone Prometheus /metrics "
                            "endpoint on this port (0 = auto-assign)")
        p.add_argument("--trace", default=None, metavar="PATH",
                       help="record host spans; write Chrome-trace JSON "
                            "here on exit (docs/OBSERVABILITY.md)")

    p_train = sub.add_parser("train", help="fit a model and checkpoint it")
    common(p_train, True)
    p_train.add_argument("--epochs", type=int, default=1)
    p_train.add_argument("--batch-size", type=int, default=None,
                         help="mini-batch size (train through the "
                              "device-feed iterator path; required for "
                              "a mid-epoch --resume to line its cursor "
                              "up, and the elastic job split unit)")
    p_train.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                         help="write sharded async autosaves here during "
                              "the fit (docs/CHECKPOINTS.md); restorable "
                              "on any topology via -m DIR")
    p_train.add_argument("--checkpoint-every", type=int, default=None,
                         metavar="N",
                         help="autosave cadence in fit ticks (requires "
                              "--checkpoint-dir; default 1 when the dir "
                              "is set)")
    p_train.add_argument("--checkpoint-keep", type=int, default=3,
                         metavar="N",
                         help="committed steps to retain under "
                              "--checkpoint-dir (older steps are "
                              "pruned); raise it when a deployment "
                              "controller (`pipeline`) eval-gates the "
                              "steps so candidates outlive the "
                              "eval+canary window")
    p_train.add_argument("--resume", default=None, metavar="auto|PATH",
                         help="resume from a sharded checkpoint: 'auto' "
                              "discovers the latest COMMITTED step under "
                              "--checkpoint-dir (no step dir named); a "
                              "path pins a root or step dir. Restores "
                              "params + updater state + cursor "
                              "(docs/FAULT_TOLERANCE.md)")
    p_train.add_argument("--elastic", type=int, default=None, metavar="N",
                         help="self-healing elastic training across N "
                              "out-of-process workers (supervisor with "
                              "failure detection, bounded respawn, "
                              "straggler defense, elastic resume — "
                              "docs/FAULT_TOLERANCE.md)")
    p_train.add_argument("--max-respawns", type=int, default=3,
                         help="total replacement workers the elastic "
                              "supervisor may spawn before declaring "
                              "capacity durably lost (then: resharded "
                              "resume on the survivors)")
    p_train.add_argument("--straggler-factor", type=float, default=4.0,
                         help="evict-and-respawn a worker persistently "
                              "slower than the wave median by this "
                              "factor")
    p_train.add_argument("--status-port", type=int, default=None,
                         help="elastic: serve the supervisor's "
                              "status/healthz/metrics endpoint on this "
                              "port (0 = auto-assign)")
    p_train.add_argument("--run-timeout", type=float, default=3600.0,
                         help="elastic: overall run deadline in seconds")
    p_train.add_argument("--state-dir", default=None, metavar="DIR",
                         help="elastic: crash-safe control plane — "
                              "journal supervisor membership here "
                              "(supervisor.journal) so a restarted "
                              "supervisor (see `watchdog`) re-adopts "
                              "its surviving workers warm instead of "
                              "respawning them "
                              "(docs/FAULT_TOLERANCE.md)")
    p_train.add_argument("--compile-cache", default=None,
                         metavar="DIR|auto|off",
                         help="persistent AOT program cache for the "
                              "jitted train/eval steps; `auto` "
                              "co-locates with --checkpoint-dir "
                              "(docs/WARMUP.md). Elastic workers "
                              "inherit it through the spawner env")
    telemetry_flags(p_train)
    p_train.set_defaults(fn=cmd_train)

    p_test = sub.add_parser("test", help="evaluate a model")
    common(p_test, None)
    p_test.set_defaults(fn=cmd_test)

    p_pred = sub.add_parser("predict", help="emit class predictions")
    common(p_pred, False)
    p_pred.set_defaults(fn=cmd_predict, label_columns=0)

    p_ckpt = sub.add_parser(
        "checkpoint",
        help="inspect sharded checkpoints (docs/CHECKPOINTS.md)")
    p_ckpt.add_argument("action", choices=["inspect"],
                        help="inspect: print a checkpoint's manifest")
    p_ckpt.add_argument("dir", help="checkpoint root (or one step dir)")
    p_ckpt.add_argument("--step", type=int, default=None,
                        help="inspect this step (default: latest committed)")
    p_ckpt.add_argument("--json", action="store_true",
                        help="single-line machine-readable output incl. "
                             "the full leaf table")
    p_ckpt.set_defaults(fn=cmd_checkpoint)

    p_serve = sub.add_parser(
        "serve", help="serve a model over HTTP (docs/SERVING.md)")
    p_serve.add_argument("--model", "-m", required=True,
                         help="conf .json (fresh net), .ckpt checkpoint, "
                              "or sharded checkpoint dir")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=0,
                         help="0 = auto-assign (printed on start)")
    p_serve.add_argument("--replicas", type=int, default=None,
                         help="device replicas (default: all local)")
    p_serve.add_argument("--max-batch-size", type=int, default=64,
                         help="micro-batcher coalescing cap / top bucket")
    p_serve.add_argument("--max-delay-ms", type=float, default=2.0,
                         help="micro-batcher coalescing window")
    p_serve.add_argument("--slots", type=int, default=8,
                         help="continuous-batching decode slots for "
                              "/generate (docs/SERVING.md)")
    p_serve.add_argument("--page-size", type=int, default=16,
                         help="KV page size in tokens for the paged "
                              "decode pool")
    p_serve.add_argument("--prefix-cache",
                         action=argparse.BooleanOptionalAction,
                         default=True,
                         help="cross-request KV prefix sharing in the "
                              "decode pool (--no-prefix-cache disables; "
                              "docs/SERVING.md)")
    p_serve.add_argument("--fleet-kv", default="on",
                         choices=("on", "affinity-only", "off"),
                         help="this replica's half of the fleet KV "
                              "plane: `on` publishes the affinity "
                              "summary on /readyz AND serves "
                              "/kv/export + fetches from donors, "
                              "`affinity-only` publishes but never "
                              "ships pages, `off` disables both "
                              "(docs/FLEET.md \"Fleet KV plane\")")
    p_serve.add_argument("--kv-ship-timeout", type=float, default=2.0,
                         metavar="S",
                         help="budget for one donor page fetch + "
                              "install (seconds; request deadlines "
                              "cap it further). Raise it when donors "
                              "run compute-starved — expiry just "
                              "falls back to plain prefill "
                              "(docs/FLEET.md \"Fleet KV plane\")")
    p_serve.add_argument("--decode-kernel", default="auto",
                         choices=("auto", "pallas", "gather"),
                         help="decode attention lane: pallas streams "
                              "written KV pages from the pool (TPU), "
                              "gather materializes the dense window; "
                              "auto picks pallas on TPU inside its "
                              "envelope (docs/SERVING.md)")
    p_serve.add_argument("--transformer", default=None, metavar="SPEC",
                         help="enable /generate from a deterministically "
                              "initialized transformer: SPEC is a JSON "
                              "object (inline or a file path) of "
                              "TransformerConfig fields plus an optional "
                              "\"seed\" — every process given the same "
                              "SPEC serves bit-identical weights, which "
                              "is how fleet stream-failover drills get "
                              "interchangeable replicas (docs/FLEET.md)")
    p_serve.add_argument("--kv-pages", type=int, default=None,
                         help="size of the paged KV pool in pages "
                              "(default: slots * ceil(max_len / "
                              "page_size))")
    p_serve.add_argument("--horizon", type=int, default=1,
                         help="decode steps chained per dispatch "
                              "(docs/SERVING.md; mutually exclusive "
                              "with --speculation)")
    p_serve.add_argument("--speculation", type=int, default=0,
                         help="speculative decoding draft depth k "
                              "(0 = off): a drafter proposes k tokens "
                              "per slot and ONE widened verify step "
                              "accepts the longest target-matching "
                              "prefix — output stays bit-identical "
                              "(docs/SERVING.md)")
    p_serve.add_argument("--drafter", default="ngram",
                         choices=("ngram", "model"),
                         help="speculative drafter flavor: ngram = "
                              "zero-weight prompt lookup fed by the "
                              "prefix cache; model = a small draft "
                              "transformer (--draft-model)")
    p_serve.add_argument("--draft-model", default=None, metavar="SPEC",
                         help="draft transformer for --drafter model: "
                              "same JSON SPEC contract as "
                              "--transformer (TransformerConfig fields "
                              "+ \"seed\"); its vocab must match the "
                              "serving model's")
    p_serve.add_argument("--draft-window", type=int, default=32,
                         help="token window the draft model conditions "
                              "on (right-aligned slice of each slot's "
                              "history)")
    p_serve.add_argument("--no-warmup", dest="warmup",
                         action="store_false",
                         help="skip precompiling the bucket programs")
    p_serve.add_argument("--warmup-async", action="store_true",
                         help="open the socket first and warm up on a "
                              "background thread; /readyz answers 503 "
                              "until the precompile lands (how fleet "
                              "replicas hide spin-up, docs/FLEET.md)")
    p_serve.add_argument("--max-queue", type=int, default=None,
                         help="bound the /predict coalescing queue; "
                              "past it requests shed with 503 + "
                              "Retry-After")
    p_serve.add_argument("--batch-share", type=float, default=0.5,
                         help="weighted-fair fraction of decode slots "
                              "the batch SLO tier may hold while "
                              "interactive requests wait — interactive "
                              "preempts batch slots past it, losslessly "
                              "(docs/SERVING.md \"Priority tiers\")")
    p_serve.add_argument("--compile-cache", default=None,
                         metavar="DIR|auto|off",
                         help="persistent AOT program cache: warm "
                              "boots load serialized executables "
                              "instead of recompiling (docs/WARMUP.md)."
                              " `auto` co-locates with a model/"
                              "checkpoint DIR; unset still inherits "
                              "DL4J_TPU_COMPILE_CACHE from a spawner")
    p_serve.add_argument("--warmup-plan", default="auto",
                         metavar="auto|off|PATH",
                         help="warmup plan to replay at boot (the "
                              "program set a previous replica compiled)"
                              " and to record at shutdown; `auto` "
                              "stores it inside the compile cache, "
                              "`off` disables plan replay/recording")
    p_serve.add_argument("--role", default="unified",
                         choices=("unified", "prefill", "decode"),
                         help="disaggregated replica role announced on "
                              "/readyz: `prefill` computes prompt KV "
                              "and ships pages (never owns a stream), "
                              "`decode` owns streams; `unified` does "
                              "both (the default single-role fleet) "
                              "(docs/FLEET.md \"Disaggregated roles\")")
    p_serve.add_argument("--model-id", default=None, metavar="NAME",
                         help="model identity announced on /readyz for "
                              "multi-model fleet routing (requests "
                              "carry X-Model / \"model_id\"); unset "
                              "announces none and routes as `default`")
    p_serve.add_argument("--smoke", action="store_true",
                         help="start, print the address, shut down")
    telemetry_flags(p_serve)
    p_serve.set_defaults(fn=cmd_serve)

    p_fleet = sub.add_parser(
        "fleet",
        help="router tier over N replica server processes "
             "(docs/FLEET.md)")
    p_fleet.add_argument("--model", "-m", default=None,
                         help="checkpoint/conf served by spawned "
                              "replicas (optional with --attach)")
    p_fleet.add_argument("--replicas", type=int, default=2,
                         help="replica processes to spawn locally "
                              "(0 = attach-only)")
    p_fleet.add_argument("--attach", action="append", default=[],
                         metavar="URL",
                         help="attach an already-running replica "
                              "endpoint (repeatable)")
    p_fleet.add_argument("--host", default="127.0.0.1")
    p_fleet.add_argument("--port", type=int, default=0,
                         help="router port; 0 = auto-assign (printed)")
    p_fleet.add_argument("--heartbeat-interval", type=float, default=0.5)
    p_fleet.add_argument("--heartbeat-timeout", type=float, default=3.0,
                         help="evict a replica whose liveness probe "
                              "has not succeeded for this long")
    p_fleet.add_argument("--shed-high-water", type=int, default=None,
                         help="shed (503 + Retry-After) when this many "
                              "requests are in flight fleet-wide")
    p_fleet.add_argument("--batch-high-water", type=int, default=None,
                         help="shed BATCH-tier requests once this many "
                              "are in flight fleet-wide (default: half "
                              "of --shed-high-water) so bulk work sheds "
                              "before the interactive lane feels "
                              "pressure (docs/FLEET.md)")
    p_fleet.add_argument("--request-timeout", type=float, default=60.0,
                         help="per-hop /predict socket timeout ceiling; "
                              "requests carrying X-Deadline-Ms derive "
                              "their hop timeouts from the remaining "
                              "budget instead (docs/SERVING.md)")
    p_fleet.add_argument("--retry-budget", type=int, default=2,
                         help="max /predict retries on healthy peers "
                              "after a replica failure or timeout")
    p_fleet.add_argument("--stream-resume-attempts", type=int, default=2,
                         help="max mid-stream failover resumes per "
                              "/generate before the router gives up "
                              "with the in-band retryable error "
                              "(0 disables durable-stream failover; "
                              "docs/FLEET.md \"Stream failover\")")
    p_fleet.add_argument("--breaker-threshold", type=int, default=3,
                         help="consecutive request timeouts that trip a "
                              "replica's circuit breaker open (evicting "
                              "hung-but-TCP-alive members, docs/FLEET.md)")
    p_fleet.add_argument("--breaker-reset", type=float, default=None,
                         metavar="S",
                         help="open -> half-open wait before the /readyz "
                              "readmission probe (default: 4x the "
                              "heartbeat interval)")
    p_fleet.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                         help="enable the autoscaling hook between MIN "
                              "and MAX replicas (queue-depth driven)")
    p_fleet.add_argument("--ready-timeout", type=float, default=180.0,
                         help="wait this long for the first replica to "
                              "pass /readyz before announcing")
    p_fleet.add_argument("--serve-arg", action="append", default=[],
                         metavar="ARG",
                         help="extra flag forwarded to each spawned "
                              "replica's `serve` (repeatable)")
    p_fleet.add_argument("--fleet-kv", default="on",
                         choices=("on", "affinity-only", "off"),
                         help="fleet KV plane mode, applied to BOTH "
                              "the router (prefix-affinity placement, "
                              "donor hints) and every spawned replica "
                              "(summary publication, page shipping); "
                              "`affinity-only` routes by prefix but "
                              "never ships pages "
                              "(docs/FLEET.md \"Fleet KV plane\")")
    p_fleet.add_argument("--state-dir", default=None, metavar="DIR",
                         help="crash-safe control plane: journal "
                              "replica membership here (fleet.journal) "
                              "so a restarted router (see `watchdog`) "
                              "re-adopts the warm fleet via /readyz — "
                              "zero respawns, zero recompiles "
                              "(docs/FLEET.md router-restart runbook)")
    p_fleet.add_argument("--compile-cache", default=None,
                         metavar="DIR|auto|off",
                         help="persistent AOT program cache exported "
                              "to every spawned replica: respawns and "
                              "autoscale spin-ups boot warm "
                              "(docs/WARMUP.md); `auto` co-locates "
                              "with a model/checkpoint DIR")
    p_fleet.add_argument("--roles", default=None,
                         metavar="ROLE=N[,ROLE=N...]",
                         help="disaggregated role pools to spawn, e.g. "
                              "`prefill=1,decode=2`: each pool's "
                              "replicas get the matching `--role` "
                              "serve flag and are autoscaled "
                              "independently (docs/FLEET.md "
                              "\"Disaggregated roles\"). Replaces "
                              "--replicas for spawning")
    p_fleet.add_argument("--models", default=None,
                         metavar="NAME=PATH[,NAME=PATH...]",
                         help="multi-model fleet: spawn one pool per "
                              "named model (each replica serves PATH "
                              "and announces `--model-id NAME`); "
                              "combined with --roles every model gets "
                              "the full role layout. Requests route by "
                              "X-Model / \"model_id\"")
    p_fleet.add_argument("--smoke", action="store_true",
                         help="start, print the address, shut down "
                              "(stops spawned replicas)")
    telemetry_flags(p_fleet)
    p_fleet.set_defaults(fn=cmd_fleet)

    p_watch = sub.add_parser(
        "watchdog",
        help="restart-under-backoff wrapper supervising a control-"
             "plane subcommand (docs/FAULT_TOLERANCE.md)")
    p_watch.add_argument("--max-restarts", type=int, default=10,
                         help="give up after this many non-zero exits")
    p_watch.add_argument("--backoff", type=float, default=1.0,
                         help="initial restart backoff in seconds "
                              "(doubles per restart)")
    p_watch.add_argument("--backoff-max", type=float, default=30.0,
                         help="backoff ceiling in seconds")
    p_watch.add_argument("--stop-grace", type=float, default=10.0,
                         help="seconds a forwarded SIGTERM/SIGINT may "
                              "take before the child is killed")
    p_watch.add_argument("cmd", nargs=argparse.REMAINDER,
                         help="the wrapped subcommand, after `--`: "
                              "e.g. `-- train --elastic 2 "
                              "--state-dir S ...`")
    p_watch.set_defaults(fn=cmd_watchdog)

    p_eval = sub.add_parser(
        "eval",
        help="one-shot held-out eval of a checkpoint — the pipeline's "
             "promotion gate, runnable by hand (docs/PIPELINE.md)")
    p_eval.add_argument("--model", "-m", required=True,
                        help="conf .json (fresh net), .ckpt checkpoint, "
                             "or sharded checkpoint dir")
    p_eval.add_argument("--data", required=True,
                        help="held-out CSV (features + trailing labels)")
    p_eval.add_argument("--label-columns", type=int, default=1,
                        help="trailing label columns (1 = integer class)")
    p_eval.add_argument("--step", type=int, default=None,
                        help="pin a committed step in a sharded dir "
                             "(default: latest committed)")
    p_eval.add_argument("--json", action="store_true",
                        help="single-line machine-readable output")
    p_eval.set_defaults(fn=cmd_eval)

    p_batch = sub.add_parser(
        "batch",
        help="bulk generation through a router on the batch SLO tier "
             "with crash-safe resumable progress (docs/SERVING.md "
             "\"Priority tiers\")")
    p_batch.add_argument("--url", required=True,
                         help="router (or single replica) base URL")
    p_batch.add_argument("--input", "-i", required=True,
                         help="JSONL prompts: each line a token list "
                              "or {\"prompt\": [...], "
                              "\"max_tokens\": N}")
    p_batch.add_argument("--output", "-o", required=True,
                         help="JSONL results, one line per input row "
                              "({row, tokens, finish_reason}); "
                              "appended to on resume")
    p_batch.add_argument("--journal", default=None, metavar="PATH",
                         help="progress cursor journal (default: "
                              "OUTPUT.journal); delete it and the "
                              "output to restart from row 0")
    p_batch.add_argument("--max-tokens", type=int, default=16,
                         help="decode budget for rows that do not "
                              "carry their own")
    p_batch.add_argument("--batch-size", type=int, default=8,
                         help="rows per /generate request (admitted "
                              "as one group)")
    p_batch.add_argument("--eos-id", type=int, default=None,
                         help="stop rows early at this token id")
    p_batch.add_argument("--timeout", type=float, default=300.0,
                         help="per-request socket timeout — batch "
                              "work queues behind interactive "
                              "admission and may be preempted "
                              "mid-stream, so keep it generous")
    p_batch.add_argument("--max-shed-retries", type=int, default=120,
                         help="per-chunk 503 sheds to wait out before "
                              "giving up (each honors the tier-aware "
                              "Retry-After, capped at 5s a beat)")
    p_batch.add_argument("--progress", action="store_true",
                         help="print a JSON progress line per chunk")
    p_batch.set_defaults(fn=cmd_batch)

    p_pipe = sub.add_parser(
        "pipeline",
        help="crash-safe train->serve deployment controller: watch -> "
             "eval gate -> canary promote -> rollback "
             "(docs/PIPELINE.md)")
    p_pipe.add_argument("--checkpoint-dir", required=True, metavar="DIR",
                        help="sharded checkpoint root to watch for "
                             "newly COMMITTED steps (the training "
                             "side's --checkpoint-dir)")
    p_pipe.add_argument("--fleet-url", default=None, metavar="URL",
                        help="router URL of an already-running fleet "
                             "(`fleet` subcommand) to drive over HTTP")
    p_pipe.add_argument("--spawn-fleet", action="store_true",
                        help="spawn the serving fleet in-process "
                             "instead (needs -m MODEL; starts a router "
                             "+ --replicas replica processes)")
    p_pipe.add_argument("--model", "-m", default=None,
                        help="checkpoint/conf served by --spawn-fleet "
                             "replicas at boot")
    p_pipe.add_argument("--replicas", type=int, default=2,
                        help="--spawn-fleet: replica processes")
    p_pipe.add_argument("--host", default="127.0.0.1")
    p_pipe.add_argument("--port", type=int, default=0,
                        help="--spawn-fleet: router port (0 = auto)")
    p_pipe.add_argument("--ready-timeout", type=float, default=180.0,
                        help="--spawn-fleet: wait for the first replica")
    p_pipe.add_argument("--serve-arg", action="append", default=[],
                        metavar="ARG",
                        help="--spawn-fleet: extra flag forwarded to "
                             "each replica's `serve` (repeatable)")
    p_pipe.add_argument("--eval-data", default=None, metavar="CSV",
                        help="held-out CSV for the promotion gate "
                             "(omitted = gate disabled: every committed "
                             "step is canaried)")
    p_pipe.add_argument("--eval-via-fleet", action="store_true",
                        help="refresh the champion's regression "
                             "baseline by scoring --eval-data against "
                             "the LIVE fleet on the batch SLO tier "
                             "before each gate (needs --fleet-url; "
                             "docs/PIPELINE.md)")
    p_pipe.add_argument("--label-columns", type=int, default=1)
    p_pipe.add_argument("--metric", default="f1",
                        choices=("f1", "accuracy", "precision",
                                 "recall"),
                        help="gate metric from the held-out eval")
    p_pipe.add_argument("--eval-threshold", type=float, default=0.0,
                        help="absolute gate: quarantine a candidate "
                             "scoring below this")
    p_pipe.add_argument("--regression-margin", type=float, default=0.05,
                        help="relative gate: quarantine a candidate "
                             "scoring more than this below the current "
                             "champion's gate score")
    p_pipe.add_argument("--poll-interval", type=float, default=2.0,
                        help="checkpoint-dir watch interval in seconds "
                             "(bounded polling; no inotify)")
    p_pipe.add_argument("--probe", default=None, metavar="JSON",
                        help="validation probe body forwarded to the "
                             "canary's /predict before promotion, e.g. "
                             "'{\"inputs\": [[0,0,0,0]]}'")
    p_pipe.add_argument("--state-dir", default=None, metavar="DIR",
                        help="crash-safe control plane: journal the "
                             "controller's decision state here "
                             "(controller.journal) so a restart (see "
                             "`watchdog`) resumes mid-promotion to a "
                             "consistent verdict; --spawn-fleet also "
                             "journals the fleet under DIR/fleet")
    p_pipe.add_argument("--name", default=None,
                        help="pipeline label on dl4j_pipeline_* series")
    p_pipe.add_argument("--status-port", type=int, default=None,
                        help="serve the controller's status/healthz/"
                             "metrics endpoint (0 = auto-assign)")
    p_pipe.add_argument("--cycles", type=int, default=None, metavar="N",
                        help="exit 0 after N watch cycles (default: "
                             "run until stopped)")
    p_pipe.add_argument("--compile-cache", default=None,
                        metavar="DIR|auto|off",
                        help="persistent AOT program cache exported to "
                             "canary/promoted replicas; `auto` "
                             "co-locates with the watched checkpoint "
                             "dir (docs/WARMUP.md)")
    p_pipe.add_argument("--smoke", action="store_true",
                        help="start, print the announce line, shut down")
    telemetry_flags(p_pipe)
    p_pipe.set_defaults(fn=cmd_pipeline)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
