"""Input/output pre-processors between layers.

Parity: reference core/nn/conf/preprocessor/ (`ReshapePreProcessor`,
`BinomialSamplingPreProcessor`, `AggregatePreProcessor`, `OutputPreProcessor`)
and the convolution reshape pair (core/nn/layers/convolution/preprocessor/
ConvolutionInputPreProcessor.java / ConvolutionPostProcessor.java).
Each is a pure callable on arrays; serialized by registry name + args.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.config.multi_layer_configuration import register_preprocessor


class PreProcessor:
    registry_name = "base"

    def serializable_args(self) -> dict:
        return {}

    def __call__(self, x, *, rng=None):
        raise NotImplementedError


@register_preprocessor("reshape")
class ReshapePreProcessor(PreProcessor):
    """Reshape to a fixed shape, keeping the batch dimension if `keep_batch`."""

    def __init__(self, shape: Sequence[int], keep_batch: bool = True):
        self.shape = list(shape)
        self.keep_batch = keep_batch

    def serializable_args(self):
        return {"shape": self.shape, "keep_batch": self.keep_batch}

    def __call__(self, x, *, rng=None):
        if self.keep_batch:
            return jnp.reshape(x, (x.shape[0], *self.shape))
        return jnp.reshape(x, tuple(self.shape))


@register_preprocessor("binomial_sampling")
class BinomialSamplingPreProcessor(PreProcessor):
    """Bernoulli-sample activations (DBN-style stochastic binary units).
    With no rng key (inference/scoring) passes the probabilities through —
    the expectation of the sample."""

    def __call__(self, x, *, rng=None):
        if rng is None:
            return x
        return jax.random.bernoulli(rng, jnp.clip(x, 0.0, 1.0)).astype(x.dtype)


@register_preprocessor("aggregate")
class AggregatePreProcessor(PreProcessor):
    """Chain preprocessors in order (reference AggregatePreProcessor.java:
    apply each in sequence)."""

    def __init__(self, preprocessors: Sequence):
        from deeplearning4j_tpu.config.multi_layer_configuration import (
            PREPROCESSOR_REGISTRY)

        # accept live PreProcessors or their {"name", "args"} wire form
        # (the JSON round trip nests children inside this one's args)
        self.preprocessors = [
            p if isinstance(p, PreProcessor)
            else PREPROCESSOR_REGISTRY[p["name"]](**p.get("args", {}))
            for p in preprocessors]

    def serializable_args(self):
        return {"preprocessors": [
            {"name": p.registry_name, "args": p.serializable_args()}
            for p in self.preprocessors]}

    def __call__(self, x, *, rng=None):
        keys = (jax.random.split(rng, len(self.preprocessors))
                if rng is not None else [None] * len(self.preprocessors))
        for p, k in zip(self.preprocessors, keys):
            x = p(x, rng=k)
        return x


@register_preprocessor("conv_input")
class ConvolutionInputPreProcessor(PreProcessor):
    """Flat (B, rows*cols*channels) -> NHWC (B, rows, cols, channels).

    Parity: reference ConvolutionInputPreProcessor.java (which targets NCHW);
    here the layout is NHWC — the native layout for TPU convolutions, where
    the channel dimension maps onto the MXU lanes.
    """

    def __init__(self, rows: int, cols: int, channels: int = 1):
        self.rows, self.cols, self.channels = rows, cols, channels

    def serializable_args(self):
        return {"rows": self.rows, "cols": self.cols, "channels": self.channels}

    def __call__(self, x, *, rng=None):
        return jnp.reshape(x, (x.shape[0], self.rows, self.cols, self.channels))


@register_preprocessor("conv_output")
class ConvolutionPostProcessor(PreProcessor):
    """NHWC -> flat (B, H*W*C) after a conv stack (ConvolutionPostProcessor.java)."""

    def __call__(self, x, *, rng=None):
        return jnp.reshape(x, (x.shape[0], -1))
