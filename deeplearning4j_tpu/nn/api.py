"""Core model/layer interfaces.

Parity: reference core/nn/api/Model.java:34-193 (fit/score/params/gradient/
paramTable) and Layer.java:33-94 (activate/preOutput/merge/transpose). The
TPU-native contract is functional: a Layer object is a stateless definition
bound to its NeuralNetConfiguration; parameters live in pytrees threaded
through pure `apply` functions so jit/grad/vmap/shard_map compose. The
stateful DL4J-style surface (fit/params/setParams) is layered on top in
MultiLayerNetwork.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Protocol, runtime_checkable

import jax

Params = Dict[str, Any]  # named-parameter table, e.g. {"W": ..., "b": ...}


@runtime_checkable
class Layer(Protocol):
    """A layer definition. Stateless; parameters are explicit pytrees."""

    conf: Any

    def init_params(self, key: jax.Array) -> Params:
        """Create this layer's named-parameter table (ParamInitializer parity:
        reference core/nn/params/DefaultParamInitializer.java:29-50)."""
        ...

    def pre_output(self, params: Params, x, **kw):
        """Affine/pre-activation output (reference BaseLayer.preOutput :176)."""
        ...

    def activate(self, params: Params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        """Forward activation (reference BaseLayer.activate :202)."""
        ...


class PretrainLayer(Layer, Protocol):
    """A layer trainable unsupervised (RBM / AutoEncoder family).

    Parity: reference core/nn/layers/BasePretrainNetwork.java — exposes an
    unsupervised loss over (params, batch, rng) that layer-wise pretraining
    minimizes, plus a reconstruction transform.
    """

    def pretrain_loss(self, params: Params, x, rng: jax.Array):
        ...

    def reconstruct(self, params: Params, x):
        ...


def merge_params(a: Params, b: Params, n: int) -> Params:
    """Parameter-averaging merge: a += (b - a) / n.

    Parity: reference MultiLayerNetwork.merge (core/nn/multilayer/
    MultiLayerNetwork.java:1361) and BaseLayer.merge (:270) — the primitive
    the distributed parameter-averaging runtimes are built on.
    """
    return jax.tree_util.tree_map(lambda x, y: x + (y - x) / n, a, b)
