"""Base feed-forward layers + the layer registry.

Parity: reference core/nn/layers/BaseLayer.java (dense affine + string-named
activation, :176/:202), OutputLayer.java (losses via ops.losses — gradients
come from jax.grad instead of the hand-coded per-loss switch at :131-163),
and the factory dispatch in core/nn/layers/factory/LayerFactories.java:20-30
(here: a name -> class registry resolved from conf.layer).

TPU notes: the affine runs in `conf.compute_dtype` (bfloat16 on the MXU when
configured) with float32 parameters; dropout/dropconnect use explicit PRNG
keys.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import apply_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import loss_fn

LAYER_REGISTRY: Dict[str, Type["BaseLayer"]] = {}


def register_layer(name: str) -> Callable[[Type["BaseLayer"]], Type["BaseLayer"]]:
    def deco(cls):
        LAYER_REGISTRY[name] = cls
        cls.layer_name = name
        return cls

    return deco


def apply_dropout(rng: Optional[jax.Array], x, rate: float,
                  training: bool = True):
    """Inverted dropout: keep-mask + 1/(1-rate) scale. No-op when not
    training, rate == 0, or no key is provided (inference = expectation)."""
    if not training or rate <= 0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return x * keep / (1.0 - rate)


def make_layer(conf) -> "BaseLayer":
    """Resolve conf.layer through the registry (LayerFactories parity)."""
    if conf.layer.lower() not in LAYER_REGISTRY:
        # Layer providers register on import; pull them all in so configs
        # restored in a fresh process (CLI, scaleout performers) resolve.
        import deeplearning4j_tpu.models  # noqa: F401  registers model layers
        import deeplearning4j_tpu.attention  # noqa: F401  self_attention
    try:
        cls = LAYER_REGISTRY[conf.layer.lower()]
    except KeyError:
        raise ValueError(
            f"Unknown layer type {conf.layer!r}; known: {sorted(LAYER_REGISTRY)}"
        ) from None
    return cls(conf)


@register_layer("dense")
class BaseLayer:
    """Dense affine + activation. Reference core/nn/layers/BaseLayer.java."""

    #: parameter names initialized to zero (reference initializers zero all
    #: bias-like variables: b, visible bias vb, recursive encoder bias c/bU)
    BIAS_NAMES = ("b", "vb", "c", "bU", "bias")

    def __init__(self, conf):
        self.conf = conf

    @classmethod
    def is_bias(cls, name: str) -> bool:
        return name in cls.BIAS_NAMES or name.startswith("b")

    # ------------------------------------------------------------- params
    def param_shapes(self) -> Dict[str, tuple]:
        c = self.conf
        return {"W": (c.n_in, c.n_out), "b": (1, c.n_out)}

    def init_params(self, key: jax.Array):
        """DefaultParamInitializer parity: W via weight-init scheme, b zeros
        (reference core/nn/params/DefaultParamInitializer.java:29-50)."""
        c = self.conf
        shapes = self.param_shapes()
        keys = jax.random.split(key, len(shapes))
        params = {}
        for (name, shape), k in zip(sorted(shapes.items()), keys):
            if self.is_bias(name):
                params[name] = jnp.zeros(shape, jnp.dtype(c.dtype))
            else:
                params[name] = init_weights(k, shape, c.weight_init, c.dist,
                                            jnp.dtype(c.dtype))
            self.conf.variable(name)
        return params

    # ------------------------------------------------------------ forward
    def _affine(self, params, x, W_name="W", b_name="b"):
        c = self.conf
        cd = jnp.dtype(c.compute_dtype)
        y = jnp.dot(x.astype(cd), params[W_name].astype(cd),
                    preferred_element_type=jnp.float32)
        return y.astype(jnp.dtype(c.dtype)) + params[b_name]

    def pre_output(self, params, x, *, rng: Optional[jax.Array] = None,
                   training: bool = False):
        """x @ W + b, with optional dropconnect on W when training
        (reference MultiLayerNetwork dropconnect mask :515)."""
        if training and self.conf.use_drop_connect and self.conf.dropout > 0 \
                and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.conf.dropout,
                                        params["W"].shape)
            params = dict(params, W=params["W"] * keep)
        return self._affine(params, x)

    def activate(self, params, x, *, rng: Optional[jax.Array] = None,
                 training: bool = False):
        c = self.conf
        drop_rng = pre_rng = None
        if rng is not None:
            pre_rng, drop_rng = jax.random.split(rng)
        act = apply_activation(c.activation_function,
                               self.pre_output(params, x, rng=pre_rng,
                                               training=training))
        if not c.use_drop_connect:
            act = apply_dropout(drop_rng, act, c.dropout, training)
        return act

    __call__ = activate


@register_layer("output")
class OutputLayer(BaseLayer):
    """Classification/regression head.

    Reference core/nn/layers/OutputLayer.java — `score` (:72) is the configured
    loss over the activated output plus L2; the per-loss hand-coded gradient
    switch (:131-163) is replaced by autodiff over `loss`.
    """

    def loss(self, params, x, labels, *, rng=None, training: bool = False,
             weights=None):
        """Unregularized data loss; L2 lives in MultiLayerNetwork.loss_fn so
        it is applied exactly once per layer across all solver paths.
        `weights` (per-example, leading dim) masks device-feed padding rows
        out of the mean — see datasets/device_feed.py."""
        c = self.conf
        out = self.activate(params, x, rng=rng, training=training)
        return loss_fn(c.loss_function)(labels, out, weights)
