from deeplearning4j_tpu.nn import preprocessors  # noqa: F401  (registers)
from deeplearning4j_tpu.nn.layers import LAYER_REGISTRY, make_layer  # noqa: F401
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
