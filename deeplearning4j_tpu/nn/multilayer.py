"""The stacked network.

Parity: reference core/nn/multilayer/MultiLayerNetwork.java (1,596 LoC) —
init with nIn/nOut inference (:331-386), layer-wise `pretrain` (:142/:195),
`feedForward` (:457), `fit` (:1021/:1136), `finetune` (:1044), `output`/
`predict` (:1197/:1107), `score` (:1265), flat param pack/unpack
(params :784, setParameters :1420, pack :831, unPack :920), and the
parameter-averaging `merge` (:1361).

TPU-native design: parameters are a pytree ({layer index -> named-param
table}); forward/loss are pure functions of (params, batch, rng) so the
whole training step jits into one XLA program per config. The reference's
three hand-written backprop variants (computeDeltas/computeDeltas2/
computeDeltasR) are replaced by jax.grad / jax.jvp on the same loss.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.config.multi_layer_configuration import MultiLayerConfiguration
from deeplearning4j_tpu.datasets.device_feed import (DEFAULT_MIN_BUCKET,
                                                     DeviceFeed, bucket_for,
                                                     feed_mask, pad_rows)
from deeplearning4j_tpu.nn.api import merge_params
from deeplearning4j_tpu.nn.layers import make_layer
from deeplearning4j_tpu.optimize.guardian import (GuardianAbort,
                                                  guarded_update, make_guard)
from deeplearning4j_tpu.optimize.solver import Solver
from deeplearning4j_tpu.optimize.updater import NetworkGradientUpdater
from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry.trace import span
from deeplearning4j_tpu.testing import chaos
from deeplearning4j_tpu.utils.jitcache import jit_cache_size
from deeplearning4j_tpu.utils.sanitize import validate_batch

log = logging.getLogger(__name__)

# telemetry (docs/OBSERVABILITY.md): host-side counters only — nothing
# here syncs a device value, so the training math is bit-identical with
# telemetry on or off. Loss is gauged only where a float(score) host
# sync already exists (listener dispatch / fit_scan's return).
_M_STEPS = telemetry.counter(
    "dl4j_train_steps", "supervised train steps dispatched")
_M_EXAMPLES = telemetry.counter(
    "dl4j_train_examples", "example rows dispatched (incl. bucket padding)")
_M_EPOCHS = telemetry.counter("dl4j_train_epochs", "training epochs run")
_M_STEP_S = telemetry.histogram(
    "dl4j_train_step_seconds",
    "wall time per train step; source=fit is per-step dispatch wall "
    "time, source=scan is the per-step average of a compiled epoch, "
    "source=parallel is the DP/ZeRO-1/TP trainer dispatch loop, "
    "source=listener is StepTimeListener's listener-to-listener time")
_M_LOSS = telemetry.gauge(
    "dl4j_train_loss", "last host-synced training score")


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration,
                 params: Optional[jnp.ndarray] = None):
        """`params`, if given, is a packed flat vector — the reference's
        canonical checkpoint constructor `MultiLayerNetwork(String confJson,
        INDArray params)` (MultiLayerNetwork.java:91)."""
        self.conf = conf
        self._infer_layer_sizes()
        self.layers = [make_layer(c) for c in conf.confs]
        self._params: Optional[Dict[str, dict]] = None
        self._unravel = None
        self._updater_state = None
        self._train_step = None
        self._train_step_guarded = None
        self._predict_step = None
        self._finetune_solver = None
        self._batch_solver = None
        self._scan_steps: Dict[tuple, object] = {}
        self._pretrain_solvers: Dict[int, Solver] = {}
        self._pending_params = params
        self._iteration_count = 0
        self.listeners: List = []
        self._key = jax.random.PRNGKey(conf.confs[0].seed if conf.confs else 0)
        self.init()
        # recompile counters surface as dl4j_jit_programs{cache=...}
        # (weak-ref'd: watching never extends this network's lifetime)
        from deeplearning4j_tpu.telemetry import device as _tdev
        _tdev.watch_jit_cache("train_step", self.train_step_cache_size)
        _tdev.watch_jit_cache("predict_step", self.predict_step_cache_size)

    # ------------------------------------------------------------- set-up
    def _infer_layer_sizes(self) -> None:
        """nIn/nOut inference from hiddenLayerSizes (reference init:331-386 —
        the reference mutates conf during init; we replicate the inference)."""
        sizes = self.conf.hidden_layer_sizes
        if not sizes:
            return
        confs = self.conf.confs
        if len(confs) != len(sizes) + 1:
            raise ValueError(
                f"hidden_layer_sizes of length {len(sizes)} requires "
                f"{len(sizes) + 1} layer confs, got {len(confs)}")
        n_in0, n_out_last = confs[0].n_in, confs[-1].n_out
        dims = [n_in0, *sizes, n_out_last]
        for i, c in enumerate(confs):
            c.n_in, c.n_out = dims[i], dims[i + 1]

    def init(self) -> None:
        """Initialize parameters (reference MultiLayerNetwork.init :331)."""
        self._key, init_key = jax.random.split(self._key)
        keys = jax.random.split(init_key, max(1, len(self.layers)))
        self._params = {
            str(i): layer.init_params(k)
            for i, (layer, k) in enumerate(zip(self.layers, keys))
        }
        _, self._unravel = ravel_pytree(self._params)
        self._updater_state = None
        self._train_step = None
        self._train_step_guarded = None
        self._predict_step = None
        self._finetune_solver = None
        self._batch_solver = None
        self._scan_steps = {}
        self._pretrain_solvers = {}
        if self._pending_params is not None:
            self.set_parameters(self._pending_params)
            self._pending_params = None

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def set_listeners(self, listeners: Sequence) -> None:
        self.listeners = list(listeners)

    # ------------------------------------------------------------ forward
    def _layer_input(self, i: int, x, rng=None):
        pp = self.conf.input_preprocessors.get(i)
        return pp(x, rng=rng) if pp is not None else x

    def _layer_output(self, i: int, act, rng=None):
        pp = self.conf.output_preprocessors.get(i)
        return pp(act, rng=rng) if pp is not None else act

    def feed_forward_fn(self, params, x, rng: Optional[jax.Array] = None,
                        training: bool = False) -> List[jnp.ndarray]:
        """Pure feed-forward returning [input, act_0, ..., act_L]
        (reference feedForward :457)."""
        acts = [x]
        cur = x
        n = len(self.layers)
        keys = (jax.random.split(rng, 2 * n) if rng is not None
                else [None] * (2 * n))
        for i, layer in enumerate(self.layers):
            cur = self._layer_input(i, cur, rng=keys[2 * i])
            cur = layer.activate(params[str(i)], cur, rng=keys[2 * i + 1],
                                 training=training)
            cur = self._layer_output(i, cur)
            acts.append(cur)
        return acts

    def loss_fn(self, params, x, labels, rng: Optional[jax.Array] = None,
                training: bool = False, weights=None):
        """Full-network supervised loss: feed-forward into the output layer's
        configured loss (reference score :1265 via OutputLayer.score), plus
        per-layer L2 (the reference applies L2 per-variable in
        GradientAdjustment.java:66-113; defining it in the loss keeps every
        solver path — SGD, CG, LBFGS, HF — consistently regularized).

        `weights` (per-example over the batch dim) masks device-feed
        padding rows out of the data loss: zero-weight rows contribute
        zero loss/gradient and the mean divides by the real count, so
        shape bucketing never changes the math. None (the default) is the
        historical unweighted path, bit-identical to before."""
        n = len(self.layers)
        keys = (jax.random.split(rng, 2 * n) if rng is not None
                else [None] * (2 * n))
        cur = x
        for i, layer in enumerate(self.layers[:-1]):
            cur = self._layer_input(i, cur, rng=keys[2 * i])
            cur = layer.activate(params[str(i)], cur, rng=keys[2 * i + 1],
                                 training=training)
            cur = self._layer_output(i, cur)
        cur = self._layer_input(n - 1, cur, rng=keys[2 * n - 2])
        score = self.layers[-1].loss(params[str(n - 1)], cur, labels,
                                     rng=keys[2 * n - 1],
                                     training=training, weights=weights)
        for i, layer in enumerate(self.layers):
            c = layer.conf
            if c.use_regularization and c.l2 > 0:
                for name, value in params[str(i)].items():
                    if not layer.is_bias(name):
                        score = score + 0.5 * c.l2 * jnp.sum(jnp.square(value))
        return score

    # -------------------------------------------------------------- train
    def has_pretrain_layers(self) -> bool:
        return any(hasattr(layer, "pretrain_loss") for layer in self.layers)

    def _iter_batches(self, data):
        """Yield feature arrays from a DataSetIterator or a single array."""
        if hasattr(data, "reset"):
            data.reset()
            for ds in data:
                yield jnp.asarray(ds.features)
        else:
            yield jnp.asarray(data)

    def pretrain(self, data) -> None:
        """Layer-wise unsupervised pretraining (reference pretrain :142/:195):
        feed each batch through the already-trained lower layers, fit each
        pretrain-capable layer (RBM/AE) on the resulting activations.
        `data` is a DataSetIterator or a feature array."""
        for i, layer in enumerate(self.layers[:-1]):
            if not hasattr(layer, "pretrain_loss"):
                continue
            # One solver per layer, cached across pretrain() calls: the
            # batch is a traced argument of the jitted step, so every
            # mini-batch of this layer's phase (and every later pretrain
            # pass) reuses ONE compiled program instead of recompiling
            solver = self._pretrain_solvers.get(i)
            if solver is None:
                _, unravel_i = ravel_pytree(self._params[str(i)])

                def flat_loss(vec, key, batch, *, _l=layer, _u=unravel_i):
                    return _l.pretrain_loss(_u(vec), batch, key)

                solver = Solver(layer.conf, flat_loss,
                                listeners=self.listeners, model=self,
                                rng_key=self.next_key())
                self._pretrain_solvers[i] = solver
            # the optimizer snapshots its listener list; refresh it so
            # set_listeners() calls between fits reach cached solvers
            solver.get_optimizer().listeners = list(self.listeners)
            for x in self._iter_batches(data):
                cur = x
                for j in range(i):
                    cur = self._layer_input(j, cur)
                    cur = self.layers[j].activate(self._params[str(j)], cur)
                    cur = self._layer_output(j, cur)
                cur = self._layer_input(i, cur)
                # sync=False: the returned score stays a device scalar —
                # the per-optimize float() sync is the dominant cost of
                # layer-wise pretraining through a tunneled chip, and the
                # lazy %s below only materializes it at INFO verbosity
                new_params, score = solver.optimize(
                    self._params[str(i)], cur, rng_key=self.next_key(),
                    sync=False)
                self._params[str(i)] = new_params
                log.info("Pretrained layer %d (score=%s)", i, score)

    def _resolve_feed(self, iterator, device_feed):
        """(feed, raw_source) for an iterator-driven fit."""
        if isinstance(iterator, DeviceFeed):
            return iterator, iterator.source
        if device_feed is False:
            return None, iterator
        return DeviceFeed(iterator), iterator

    def fit(self, x, labels=None, epochs: int = 1,
            device_feed: Optional[bool] = None,
            guardian=None, checkpoint_every: Optional[int] = None,
            saver=None, start_position: int = 0,
            start_epoch: int = 0, start_epoch_batch: int = 0) -> None:
        """Train. Accepts (x, labels) arrays or a DataSetIterator
        (reference fit(DataSet) :1172 / fit(DataSetIterator) :1021).
        Pretraining (if configured) runs ONCE over the data, then the
        supervised phase runs for `epochs`.

        Iterator-driven runs go through the device-feed pipeline by
        default (datasets/device_feed.py): ragged batches are padded to
        shape buckets with the real count threaded into the masked loss,
        so the jitted step compiles once per bucket instead of once per
        batch shape, and H2D transfers prefetch ahead of the step. Pass
        `device_feed=False` for the legacy per-shape path, or pass a
        DeviceFeed instance directly as `x` for custom buckets/prefetch.

        Fault tolerance (optimize/guardian.py, docs/FAULT_TOLERANCE.md):
        `guardian=` (a GuardianPolicy, or True for defaults) switches to
        the guarded train step — non-finite grad/loss steps are skipped
        on device, persistent trouble rolls back to a last-good snapshot
        with LR backoff, and `GuardianAbort` fires when the rollback
        budget runs out (the network is left on the last-good state).
        `checkpoint_every=N` autosaves a resumable checkpoint (params +
        updater state + batch cursor) every N batches through `saver`
        (default: rotating DefaultModelSaver); any configured saver also
        arms a SIGTERM hook that flushes a final checkpoint and raises
        `TrainingPreempted`. With everything off (the default) this is
        the historical code path, bit for bit. Guardian requires the
        iteration_gradient_descent backprop algorithm.

        Resuming a checkpointed run: `start_position`/`start_epoch`/
        `start_epoch_batch` seed the guard's cursors with the restored
        checkpoint's `iterator_position` and `metadata` epoch fields,
        so subsequent autosaves continue the step numbering (no
        collision with committed step dirs) and record a truthful
        within-epoch cursor (a SECOND resume fast-forwards correctly) —
        pair with `DeviceFeed.fast_forward(epoch_batch)` to position
        the data stream (docs/FAULT_TOLERANCE.md, `cli train
        --resume`)."""
        guard = make_guard(self, guardian, checkpoint_every, saver,
                           start_position=start_position,
                           start_epoch=start_epoch,
                           start_epoch_batch=start_epoch_batch)
        if guard is None:
            return self._fit_impl(x, labels, epochs, device_feed, None)
        with guard:
            return self._fit_impl(x, labels, epochs, device_feed, guard)

    def _fit_impl(self, x, labels, epochs, device_feed, guard) -> None:
        """One fit body for the guarded and historical paths — with
        guard=None every guard hook is skipped and this is the legacy
        code path, bit for bit."""
        if labels is None:  # iterator protocol
            iterator = x
            feed, raw = self._resolve_feed(iterator, device_feed)
            if self.conf.pretrain and self.has_pretrain_layers():
                self.pretrain(raw)  # host-driven per-layer: unguarded
            for _ in range(epochs):
                _M_EPOCHS.inc()
                if guard is not None:
                    guard.begin_epoch()
                if feed is not None:
                    for fb in feed:
                        self._fit_supervised(fb.features, fb.labels,
                                             n_valid=fb.n_valid, guard=guard)
                        if guard is not None:
                            guard.tick()
                else:
                    iterator.reset()
                    for ds in iterator:
                        self._fit_supervised(jnp.asarray(ds.features),
                                             jnp.asarray(ds.labels),
                                             guard=guard)
                        if guard is not None:
                            guard.tick()
            return
        x, labels = jnp.asarray(x), jnp.asarray(labels)
        validate_batch(x, labels, n_in=self.layers[0].conf.n_in
                       if not self.conf.input_preprocessors.get(0) else None,
                       n_out=self.layers[-1].conf.n_out, context="fit")
        if self.conf.pretrain and self.has_pretrain_layers():
            self.pretrain(x)
        for _ in range(epochs):
            _M_EPOCHS.inc()
            if guard is not None:
                guard.begin_epoch()
            self._fit_supervised(x, labels, guard=guard)
            if guard is not None:
                guard.tick()

    def _fit_supervised(self, x, labels, n_valid=None, guard=None) -> None:
        if self.conf.backprop:
            self._backprop_fit(x, labels, n_valid=n_valid, guard=guard)
        else:
            if guard is not None and guard.guarded:
                raise ValueError(
                    "guardian= requires the backprop iteration_gradient_"
                    "descent path; the finetune path is host-driven "
                    "(autosave via checkpoint_every= still works)")
            if n_valid is not None:
                # the finetune path is host-driven and per-layer; strip
                # the bucketing padding instead of threading a mask
                # through the frozen-feature solver (shape-specialized —
                # acceptable on this legacy non-backprop path)
                n = int(n_valid)
                x, labels = x[:n], labels[:n]
            self.finetune(x, labels)

    def fit_scan(self, x, labels, batch_size: int, epochs: int = 1,
                 pad_partial: bool = False, guardian=None,
                 checkpoint_every: Optional[int] = None,
                 saver=None) -> float:
        """Whole-epoch training as ONE compiled program: minibatches are
        a leading scan axis and `lax.scan` carries (params, updater
        state) through every step on-device — zero per-step host
        dispatch. Beyond-parity alternative path for the
        iteration_gradient_descent algorithm.

        This is the preferred training path whenever per-step host
        dispatch costs anything (it always does through a tunneled
        chip): under the honest D2H-synced protocol the 784-2048-1024-10
        bench config measures ~2.2 ms/step inside the scan vs ~20 ms per
        dispatched `fit()` step on tunneled v5e. (An earlier note here
        claimed the opposite by ~15x — that measurement trusted
        `block_until_ready`, which on the tunnel returns before the
        dispatched work completes; see BASELINE.md "timing protocol".)
        Caveat: `epochs` is a static arg — each distinct value compiles
        its own program.

        `x`: (N, features). When N is not a multiple of batch_size the
        tail is truncated (historical behavior) unless
        `pad_partial=True`, which zero-pads the last minibatch to
        batch_size and scans a per-batch example count alongside so the
        masked loss and the updater's ÷batchSize use the real counts —
        the device-feed masking semantics (docs/DEVICE_FEED.md), inside
        the scan. Returns the final batch's score.

        `guardian=` fuses the guarded commit INTO the scan body (a
        non-finite minibatch is skipped on device, the skip counter
        rides the scan carry) and drives epochs one compiled call each
        so the host-side ladder/autosave/preemption hooks run between
        epochs — one program either way. The ladder's cadences
        (check_every etc.) stay denominated in batches (each epoch
        advances them by n_batches); `checkpoint_every=` counts
        epochs."""
        conf0 = self.layers[-1].conf
        if conf0.optimization_algo.lower() != "iteration_gradient_descent":
            raise ValueError("fit_scan supports iteration_gradient_descent")
        x, labels = jnp.asarray(x), jnp.asarray(labels)
        validate_batch(x, labels, n_in=self.layers[0].conf.n_in
                       if not self.conf.input_preprocessors.get(0) else None,
                       n_out=self.layers[-1].conf.n_out, context="fit_scan")
        n_real = x.shape[0]
        tail = n_real % batch_size
        if pad_partial and tail:
            pad = batch_size - tail
            x = jnp.concatenate(
                [x, jnp.zeros((pad, *x.shape[1:]), x.dtype)])
            labels = jnp.concatenate(
                [labels, jnp.zeros((pad, *labels.shape[1:]), labels.dtype)])
        n = x.shape[0] // batch_size * batch_size
        if n == 0:
            raise ValueError(
                f"batch_size {batch_size} exceeds {x.shape[0]} examples")
        n_batches = n // batch_size
        xb = x[:n].reshape(n_batches, batch_size, *x.shape[1:])
        yb = labels[:n].reshape(n_batches, batch_size,
                                *labels.shape[1:])
        # no tail -> every count would be batch_size: reuse the cheaper
        # unmasked program instead of compiling the masked epoch for it
        masked = bool(pad_partial and tail)
        counts = None
        if masked:  # masked implies a nonzero tail
            counts = np.full((n_batches,), batch_size, np.int32)
            counts[-1] = tail
            counts = jnp.asarray(counts)

        guard = make_guard(self, guardian, checkpoint_every, saver)
        guarded = guard is not None and guard.guarded
        key = (masked, guarded)
        if key not in self._scan_steps:
            self._scan_steps[key] = self._build_scan_step(masked, guarded)

        if self._updater_state is None:
            self._updater_state = NetworkGradientUpdater.for_network(
                self).init(self._params)
        if guard is None:
            args = ((xb, yb, counts, int(epochs)) if masked
                    else (xb, yb, int(epochs)))
            t0 = time.perf_counter()
            with span("fit_scan", epochs=int(epochs), batches=n_batches):
                (self._params, self._updater_state,
                 score) = self._scan_steps[key](
                    self._params, self._updater_state, *args,
                    self.next_key())
                self._iteration_count += epochs * n_batches
                score = float(score)  # the one host sync of this path
            steps = epochs * n_batches
            _M_STEP_S.labels(source="scan").observe(
                (time.perf_counter() - t0) / max(1, steps))
            _M_STEPS.inc(steps)
            _M_EXAMPLES.inc(epochs * n)
            _M_EPOCHS.inc(epochs)
            _M_LOSS.set(score)
            for listener in self.listeners:
                listener.iteration_done(self, self._iteration_count - 1,
                                        score)
            return score

        # guarded/autosaved: one single-epoch program, driven per epoch so
        # the host ladder and checkpoint/preemption hooks interleave
        with guard:
            if guarded:
                guard.arm_once((self._params, self._updater_state))
            args = ((xb, yb, counts, 1) if masked else (xb, yb, 1))
            score = None
            scan_child = _M_STEP_S.labels(source="scan")
            for _ in range(epochs):
                guard.begin_epoch()
                t0 = time.perf_counter()
                if guarded:
                    with span("fit_scan_epoch", guarded=True,
                              batches=n_batches):
                        (self._params, self._updater_state, gstate,
                         score) = self._scan_steps[key](
                            self._params, self._updater_state, guard.gstate,
                            *args, self.next_key())
                    self._iteration_count += n_batches
                    try:
                        # steps=n_batches: the ladder's cadences stay in
                        # BATCHES even though observation is per-epoch
                        live, _ = guard.post_step(
                            (self._params, self._updater_state), gstate,
                            score, steps=n_batches)
                    except GuardianAbort as e:
                        self._params, self._updater_state = e.last_good
                        raise
                    self._params, self._updater_state = live
                else:
                    with span("fit_scan_epoch", batches=n_batches):
                        (self._params, self._updater_state,
                         score) = self._scan_steps[key](
                            self._params, self._updater_state, *args,
                            self.next_key())
                    self._iteration_count += n_batches
                scan_child.observe(
                    (time.perf_counter() - t0) / max(1, n_batches))
                _M_STEPS.inc(n_batches)
                _M_EXAMPLES.inc(n)
                _M_EPOCHS.inc()
                guard.tick()
            score = float(score)
            _M_LOSS.set(score)
            for listener in self.listeners:
                listener.iteration_done(self, self._iteration_count - 1,
                                        score)
            return score

    def _build_scan_step(self, masked: bool, guarded: bool):
        """Compile the whole-epoch program for fit_scan: `masked` scans
        per-batch real counts alongside (device-feed masking), `guarded`
        fuses the guardian's finite-check commit into the scan body and
        carries (gstate, skip counter) on device."""
        updater = NetworkGradientUpdater.for_network(self)
        # static n_epochs position shifts with the leading gstate arg
        static = 4 + int(masked) + int(guarded)

        @partial(jax.jit, donate_argnums=(0, 1), static_argnums=(static,))
        def epoch(params, upd_state, *rest):
            if guarded:
                gstate, *rest = rest
            else:
                gstate = None
            if masked:
                xb, yb, bn, n_epochs, rng = rest
            else:
                xb, yb, n_epochs, rng = rest
                bn = None

            def body(carry, batch):
                params, upd_state, gstate, rng = carry
                if masked:
                    bx, by, bi = batch
                    weights, count = feed_mask(bx.shape[0], bi)
                else:
                    bx, by = batch
                    weights, count = feed_mask(bx.shape[0], None)
                rng, sub = jax.random.split(rng)
                score, grads = jax.value_and_grad(self.loss_fn)(
                    params, bx, by, rng=sub, training=True,
                    weights=weights)
                updates, new_state = updater.update(
                    grads, upd_state, params, count)
                if guarded:
                    params, upd_state, gstate = guarded_update(
                        params, upd_state, updates, new_state, gstate,
                        score, grads)
                else:
                    upd_state = new_state
                    params = jax.tree_util.tree_map(
                        lambda p, u: p - u, params, updates)
                return (params, upd_state, gstate, rng), score

            xs = (xb, yb, bn) if masked else (xb, yb)

            def one_epoch(carry, _):
                carry, scores = jax.lax.scan(body, carry, xs)
                return carry, scores[-1]

            (params, upd_state, gstate, _), last_scores = jax.lax.scan(
                one_epoch, (params, upd_state, gstate, rng), None,
                length=n_epochs)
            if guarded:
                return params, upd_state, gstate, last_scores[-1]
            return params, upd_state, last_scores[-1]

        from deeplearning4j_tpu import compilecache
        return compilecache.maybe_wrap(
            epoch,
            self._aot_key(f"fit_scan|m={int(masked)}|g={int(guarded)}"),
            static_argnums=(static,))

    def _backprop_fit(self, x, labels, n_valid=None, guard=None) -> None:
        # chaos numeric-fault point (docs/FAULT_TOLERANCE.md): a "nan"
        # rule poisons this batch on the host, producing the non-finite
        # grads the guardian's on-device defense exists for; a no-op
        # (one global check) without an active plan
        x = chaos.maybe_nan("train.batch", x)
        conf0 = self.layers[-1].conf
        algo = conf0.optimization_algo.lower()
        guarded = guard is not None and guard.guarded
        if algo == "iteration_gradient_descent":
            # Hot path: one fused XLA program per step, updater state carried
            # across batches (standard minibatch SGD when num_iterations=1).
            # n_valid (device-feed path) is a TRACED count — every bucket
            # shape shares one program regardless of how full it is.
            step = self._get_train_step(guarded=guarded)
            if self._updater_state is None:
                self._updater_state = NetworkGradientUpdater.for_network(
                    self).init(self._params)
            if guarded:
                guard.arm_once((self._params, self._updater_state))
            score = None
            step_child = _M_STEP_S.labels(source="fit")
            for i in range(conf0.num_iterations):
                t0 = time.perf_counter()
                if guarded:
                    with span("train_step", guarded=True):
                        (self._params, self._updater_state, gstate,
                         score) = step(self._params, self._updater_state,
                                       guard.gstate, x, labels,
                                       self.next_key(), n_valid)
                    self._iteration_count += 1
                    try:
                        live, _ = guard.post_step(
                            (self._params, self._updater_state), gstate,
                            score)
                    except GuardianAbort as e:
                        # leave the network on the last-good state the
                        # escalation ladder kept, then surface the report
                        self._params, self._updater_state = e.last_good
                        raise
                    self._params, self._updater_state = live
                else:
                    with span("train_step"):
                        self._params, self._updater_state, score = step(
                            self._params, self._updater_state, x, labels,
                            self.next_key(), n_valid)
                    self._iteration_count += 1
                step_child.observe(time.perf_counter() - t0)
                _M_STEPS.inc()
                _M_EXAMPLES.inc(x.shape[0])
            if self.listeners:  # float() only where it always was:
                score_f = float(score)  # no-listener fits stay sync-free
                _M_LOSS.set(score_f)
                for listener in self.listeners:
                    listener.iteration_done(self, self._iteration_count - 1,
                                            score_f)
        else:
            if guarded:
                raise ValueError(
                    "guardian= supports only the iteration_gradient_descent "
                    f"algorithm (got {algo!r}); the line-search solvers "
                    "drive their own inner loop")
            if self._batch_solver is None:
                _, unravel = ravel_pytree(self._params)

                def flat_loss(vec, key, bx, by, *rest, _u=unravel):
                    # rest, when present, is the device-feed row mask
                    w = rest[0] if rest else None
                    return self.loss_fn(_u(vec), bx, by, rng=key,
                                        training=True, weights=w)

                # cached: line-search solvers (CG/LBFGS/HF) compile once;
                # the batch is a traced argument (rng_key at construction
                # marks the loss stochastic; per-batch keys come from the
                # optimize override)
                self._batch_solver = Solver(conf0, flat_loss,
                                            listeners=self.listeners,
                                            model=self,
                                            rng_key=self.next_key())
            data = (x, labels)
            if n_valid is not None:
                data += (feed_mask(x.shape[0], n_valid)[0],)
            self._params, _ = self._batch_solver.optimize(
                self._params, *data, rng_key=self.next_key(), sync=False)

    def _aot_key(self, tag: str) -> Optional[str]:
        """Persistent-compile-cache key for this network's jitted steps
        (docs/WARMUP.md): the config JSON names the program family, the
        device binds the serialized executable. None (= stay a plain
        jit) when no cache is active or the config won't serialize."""
        from deeplearning4j_tpu import compilecache

        if compilecache.active_compiler() is None:
            return None
        try:
            digest = compilecache.config_digest(self.to_json())
        except Exception:
            return None
        return f"train.{tag}:{digest}|dev={jax.devices()[0]}"

    def _get_train_step(self, guarded: bool = False):
        if guarded:
            if self._train_step_guarded is None:
                self._train_step_guarded = self._build_train_step(True)
            return self._train_step_guarded
        if self._train_step is None:
            self._train_step = self._build_train_step(False)
        return self._train_step

    def _build_train_step(self, guarded: bool):
        updater = NetworkGradientUpdater.for_network(self)

        # params/updater-state buffers are donated: the step's outputs
        # alias their HBM instead of allocating fresh buffers each
        # iteration (~1.4x step throughput on v5e for the MLP config).
        # Callers must treat the passed-in trees as consumed — the fit
        # loop rebinds self._params/_updater_state from the outputs.
        # n_valid is None (arrays path: bit-identical legacy program)
        # or a traced int32 count (device-feed path: rows >= n_valid
        # are bucketing padding, masked out of loss and ÷batchSize).
        if not guarded:
            @partial(jax.jit, donate_argnums=(0, 1))
            def step(params, upd_state, x, labels, rng, n_valid=None):
                weights, count = feed_mask(x.shape[0], n_valid)
                score, grads = jax.value_and_grad(self.loss_fn)(
                    params, x, labels, rng=rng, training=True,
                    weights=weights)
                updates, upd_state = updater.update(grads, upd_state, params,
                                                    count)
                params = jax.tree_util.tree_map(lambda p, u: p - u, params,
                                                updates)
                return params, upd_state, score

            from deeplearning4j_tpu import compilecache
            return compilecache.maybe_wrap(step, self._aot_key("step"))

        # guarded variant: an all-leaves-finite predicate over grads+loss
        # is reduced on device and the whole update commits through
        # jnp.where — a poisoned step leaves params/updater state (and the
        # updater's iteration counter) untouched and bumps the skip
        # counter. gstate.lr_scale rescales committed updates so the
        # rollback ladder can back off LR without recompiling.
        @partial(jax.jit, donate_argnums=(0, 1))
        def gstep(params, upd_state, gstate, x, labels, rng, n_valid=None):
            weights, count = feed_mask(x.shape[0], n_valid)
            score, grads = jax.value_and_grad(self.loss_fn)(
                params, x, labels, rng=rng, training=True, weights=weights)
            updates, new_state = updater.update(grads, upd_state, params,
                                                count)
            params, upd_state, gstate = guarded_update(
                params, upd_state, updates, new_state, gstate, score, grads)
            return params, upd_state, gstate, score

        from deeplearning4j_tpu import compilecache
        return compilecache.maybe_wrap(gstep, self._aot_key("gstep"))

    def train_step_cache_size(self) -> int:
        """Number of XLA programs compiled for the jitted supervised train
        step so far (unguarded + guarded variants) — the device-feed
        recompile counter. With shape bucketing this stays at the number
        of buckets actually hit (the traced n_valid never re-specializes);
        without it, one program per distinct batch shape. Returns 0
        before the first backprop step."""
        total = 0
        for step in (self._train_step, self._train_step_guarded):
            if step is None:
                continue
            size = jit_cache_size(step)
            if size < 0:
                return -1
            total += size
        return total

    def finetune(self, x, labels=None) -> None:
        """Optimize only the output layer on top of frozen features
        (reference finetune :1044/:1079 -> OutputLayer.fit). Accepts
        (x, labels) arrays or a DataSetIterator; large arrays stream the
        frozen-feature computation in batch_size chunks rather than
        feed-forwarding the whole dataset in one device batch."""
        if labels is None:  # iterator protocol
            iterator = x
            iterator.reset()
            for ds in iterator:
                self.finetune(ds.features, ds.labels)
            return
        x = jnp.asarray(x)
        hidden = self._frozen_features(x)
        out_idx = str(len(self.layers) - 1)
        out_layer = self.layers[-1]
        if self._finetune_solver is None:
            _, unravel = ravel_pytree(self._params[out_idx])

            def flat_loss(vec, hid, lab, *, _u=unravel):
                return out_layer.loss(_u(vec), hid, lab)

            # cached: repeated finetune batches (fit over a DataSetIterator)
            # reuse one compiled step — hidden/labels are traced args
            self._finetune_solver = Solver(out_layer.conf, flat_loss,
                                           listeners=self.listeners,
                                           model=self)
        new_params, _ = self._finetune_solver.optimize(
            self._params[out_idx], hidden, jnp.asarray(labels), sync=False)
        self._params[out_idx] = new_params

    def _frozen_features(self, x, chunk_size: int = 4096) -> jnp.ndarray:
        """Features under the output layer, computed in chunks so only
        (chunk, features) activations are ever live on device."""
        if len(self.layers) < 2:
            return x
        if x.shape[0] <= chunk_size:
            return self.feed_forward_fn(self._params, x)[-2]
        outs = [self.feed_forward_fn(self._params, x[i:i + chunk_size])[-2]
                for i in range(0, x.shape[0], chunk_size)]
        return jnp.concatenate(outs, axis=0)

    # ----------------------------------------------------------- inference
    def feed_forward(self, x) -> List[jnp.ndarray]:
        x = jnp.asarray(x)
        validate_batch(x, n_in=self.layers[0].conf.n_in
                       if not self.conf.input_preprocessors.get(0) else None,
                       context="feed_forward")
        return self.feed_forward_fn(self._params, x)

    def _get_predict_step(self):
        """Cached jitted forward to the output layer — the serving-side
        twin of _get_train_step. Input batches pad to a pow2 bucket
        before the call (see output), so a ragged request/CSV stream
        compiles <= one program per bucket instead of one per shape."""
        if self._predict_step is None:
            from deeplearning4j_tpu import compilecache
            self._predict_step = compilecache.maybe_wrap(
                jax.jit(
                    lambda params, x: self.feed_forward_fn(params, x)[-1]),
                self._aot_key("predict"))
        return self._predict_step

    def output(self, x, bucketed: bool = True) -> jnp.ndarray:
        """Output-layer activations (reference output :1197).

        `bucketed=True` (default) zero-pads the batch up to the pow2
        bucket ladder and runs the cached jitted forward, slicing the
        padding back off — inference is per-row independent, so padded
        rows never touch real outputs. `bucketed=False` is the eager
        legacy path (also the escape hatch for layers with
        cross-example behavior at inference)."""
        if not bucketed:
            return self.feed_forward(x)[-1]
        x = jnp.asarray(x)
        validate_batch(x, n_in=self.layers[0].conf.n_in
                       if not self.conf.input_preprocessors.get(0) else None,
                       context="output")
        n = x.shape[0]
        b = bucket_for(n, (DEFAULT_MIN_BUCKET,))
        return self._get_predict_step()(self._params, pad_rows(x, b))[:n]

    def predict(self, x) -> np.ndarray:
        """Class predictions (reference predict :1107) — through the
        bucketed jitted forward."""
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def predict_step_cache_size(self) -> int:
        """Compiled-program count for the jitted inference forward (the
        train_step_cache_size analogue): with bucketing this stays at
        the pow2 buckets actually hit, not one per batch shape. 0 before
        the first bucketed output/predict."""
        if self._predict_step is None:
            return 0
        return jit_cache_size(self._predict_step)

    def score(self, x, labels) -> float:
        """Mean loss on (x, labels) (reference score :1265)."""
        return float(self.loss_fn(self._params, jnp.asarray(x),
                                  jnp.asarray(labels)))

    # ------------------------------------------------- params as flat vector
    @property
    def param_table(self) -> Dict[str, dict]:
        """Live per-layer parameter tree (reference paramTable). NOTE: the
        hot fit path donates these buffers to the train step — snapshot
        with `params()` (which copies into a fresh packed vector) rather
        than holding this tree across a fit()."""
        return self._params

    def params(self) -> jnp.ndarray:
        """Packed flat parameter vector (reference params :784 / pack :831)."""
        flat, _ = ravel_pytree(self._params)
        return flat

    def set_parameters(self, flat: jnp.ndarray) -> None:
        """Install a packed vector (reference setParameters :1420 / unPack :920)."""
        self._params = self._unravel(jnp.asarray(flat))

    def num_params(self) -> int:
        return int(self.params().shape[0])

    def merge(self, other: "MultiLayerNetwork", n: int) -> None:
        """Parameter averaging: this += (other - this)/n (reference merge
        :1361 — the primitive under all distributed runtimes)."""
        self._params = merge_params(self._params, other._params, n)

    # -------------------------------------------------------- serialization
    def to_json(self) -> str:
        return self.conf.to_json()

    @classmethod
    def from_config_json(cls, s: str, params: Optional[jnp.ndarray] = None
                         ) -> "MultiLayerNetwork":
        return cls(MultiLayerConfiguration.from_json(s), params=params)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(self.conf.to_json()))
        net.set_parameters(self.params())
        return net
