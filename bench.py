"""Benchmark harness: BASELINE.md configs under the honest timing protocol.

Timing protocol (v2, "amortized-chained-d2h") — see BASELINE.md for the
calibration evidence:

- The tunneled chip has a fixed ~100 ms dispatch+readback round trip per
  host->device->host cycle, and `jax.block_until_ready` returns BEFORE
  dispatched work completes, so short per-call timings are fiction in
  both directions. Every timed window here therefore (a) runs its steps
  CHAINED ON DEVICE (lax.scan / whole-epoch programs / chunked scans —
  never identical-args eager loops), (b) is sized to hundreds of ms of
  real device work so the fixed round trip amortizes below ~10-20%, and
  (c) ends with a forced D2H read (np.asarray of a result slice) before
  the clock stops.
- Each config runs REPEATS timed windows after a compile warm-up and
  reports the median.
- vs_baseline compares against a *pinned* baseline in BENCH_HISTORY.json
  (median of >= 5 separate idle-host processes at pin time, never
  overwritten by later runs). Re-pin by deleting the metric from the
  "baselines" dict. Baselines from the pre-v2 protocol are archived to
  "baselines_v1" and never compared against.

Output: after EVERY config completes, the full cumulative summary JSON
line is printed (flushed) — the last stdout line is always a valid,
maximal summary, so a driver timeout still leaves the completed configs
on record. History is likewise written incrementally.

Select a subset with BENCH_CONFIGS=mlp,lenet (default: all). A soft
budget (BENCH_BUDGET_S, default 720 s) skips configs not yet started
once exhausted, marking them "skipped" in the summary.
"""

import json
import os
import statistics
import subprocess
import time

import numpy as np

REPEATS = 3
PROTOCOL = "v2-amortized-chained-d2h"
HERE = os.path.dirname(os.path.abspath(__file__))
HIST_PATH = os.path.join(HERE, "BENCH_HISTORY.json")


def _d2h(tree) -> None:
    """Force a host read of (a sliver of) a device value: the only sync
    primitive the tunnel doesn't lie about."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    # slice ON DEVICE before fetching — device_get of the whole leaf
    # would add a full-array transfer over the tunnel to every window
    np.asarray(jax.device_get(leaf.ravel()[:1]))


def _median_rate(run_window, units_per_window, repeats=REPEATS):
    """Median units/sec over `repeats` timed windows. run_window() must
    end with a D2H read."""
    rates, secs = [], []
    for _ in range(repeats):
        start = time.perf_counter()
        run_window()
        dt = time.perf_counter() - start
        rates.append(units_per_window / dt)
        secs.append(dt)
    return statistics.median(rates), statistics.median(secs)


def _fast() -> bool:
    """True off-TPU (CI smoke): shrink workloads, keep code paths."""
    import jax

    return jax.devices()[0].platform != "tpu"


# ----------------------------------------------------------------- configs
def _mlp_net():
    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 512 if _fast() else 4096
    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).n_in(784).activation_function("relu")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(3)
            .hidden_layer_sizes([2048, 1024])
            .override(2, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=10)
            .pretrain(False)
            .build())
    return MultiLayerNetwork(conf), batch_size


def bench_mlp():
    """BASELINE config 1: MNIST 3-layer MLP, samples/sec/chip, trained
    via the whole-epoch scan path (fit_scan) so every timed step is
    chained on-device."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

    net, batch_size = _mlp_net()
    n_batches, epochs = (4, 2) if _fast() else (16, 16)
    x_np, y_np = synthetic_mnist(batch_size * n_batches)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    net.fit_scan(x, y, batch_size=batch_size, epochs=epochs)  # compile
    _d2h(net.params())
    steps = n_batches * epochs

    def window():
        net.fit_scan(x, y, batch_size=batch_size, epochs=epochs)
        _d2h(net.params())

    rate, win_s = _median_rate(window, steps * batch_size)
    return {"value": round(rate / max(1, len(jax.devices())), 2),
            "unit": "samples/sec/chip",
            "steps_per_window": steps, "window_s": round(win_s, 3)}


def bench_feed():
    """Device-feed pipeline: iterator-driven fit() over a RAGGED stream
    (N deliberately not a multiple of batch) through shape bucketing +
    async H2D prefetch — steps/sec plus a recompile counter from the
    jitted step's program cache. Unlike the scan configs this measures
    the real iterator-driven dispatch loop (per-step host dispatch is
    part of the metric — it is what the feed pipeline exists to keep off
    the chip's critical path); compiled_programs is the regression guard:
    it must stay at the bucket-hit count, not grow with epochs."""
    import math

    from deeplearning4j_tpu.datasets import DeviceFeed, ListDataSetIterator
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

    net, batch_size = _mlp_net()
    n_batches = 4 if _fast() else 16
    n = batch_size * n_batches + batch_size // 3  # ragged last batch
    x_np, y_np = synthetic_mnist(n)
    feed = DeviceFeed(ListDataSetIterator(DataSet(x_np, y_np), batch_size),
                      prefetch=2)
    epochs = 1 if _fast() else 4
    steps_per_epoch = math.ceil(n / batch_size)

    net.fit(feed, epochs=1)  # compile every bucket program
    _d2h(net.params())
    programs_after_warmup = net.train_step_cache_size()

    def window():
        net.fit(feed, epochs=epochs)
        _d2h(net.params())

    rate, win_s = _median_rate(window, epochs * steps_per_epoch)
    programs = net.train_step_cache_size()
    # a negative counter means the private _cache_size API drifted —
    # report null rather than a fake "0 recompiles"
    counters_ok = programs >= 0 and programs_after_warmup >= 0
    return {"value": round(rate, 2), "unit": "steps/sec",
            "batch_size": batch_size, "ragged_n": n,
            "compiled_programs": programs if counters_ok else None,
            "recompiled_after_warmup":
                (programs - programs_after_warmup) if counters_ok else None,
            "feed": feed.stats(),
            "steps_per_window": epochs * steps_per_epoch,
            "window_s": round(win_s, 3)}


def bench_lenet():
    """BASELINE config 2: LeNet-5-style CNN on MNIST, per-step time.
    Reference path: core/nn/layers/convolution/
    ConvolutionDownSampleLayer.java:52."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.preprocessors import (
        ConvolutionInputPreProcessor, ConvolutionPostProcessor)
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 256 if _fast() else 1024
    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).activation_function("relu")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(4)
            .override(0, layer="conv", filter_size=[5, 5], stride=[2, 2],
                      num_in_feature_maps=1, num_feature_maps=6)
            .override(1, layer="conv", filter_size=[5, 5], stride=[2, 2],
                      num_in_feature_maps=6, num_feature_maps=16)
            .override(2, layer="dense", n_in=4 * 4 * 16, n_out=120)
            .override(3, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_in=120, n_out=10)
            .input_preprocessor(0, ConvolutionInputPreProcessor(28, 28, 1))
            .input_preprocessor(2, ConvolutionPostProcessor())
            .pretrain(False)
            .build())
    net = MultiLayerNetwork(conf)
    n_batches, epochs = (4, 2) if _fast() else (8, 32)
    x_np, y_np = synthetic_mnist(batch_size * n_batches)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    net.fit_scan(x, y, batch_size=batch_size, epochs=epochs)  # compile
    _d2h(net.params())
    steps = n_batches * epochs

    def window():
        net.fit_scan(x, y, batch_size=batch_size, epochs=epochs)
        _d2h(net.params())

    rate, win_s = _median_rate(window, steps)
    return {"value": round(1000.0 / rate, 3), "unit": "ms/step",
            "lower_is_better": True, "batch_size": batch_size,
            "steps_per_window": steps, "window_s": round(win_s, 3)}


def bench_dbn():
    """BASELINE config 4: DBN (RBM stack) pretrain + finetune,
    samples/sec/chip over the whole pretrain+finetune pass. The solver
    iterations dispatch eagerly (the pretrain path is host-driven), so
    the window batches several full fit() passes and the per-dispatch
    tunnel cost is reported as part of the metric — it is the honest
    end-to-end cost of this host-in-the-loop training mode. Reference
    path: core/models/featuredetectors/rbm/RBM.java:105 +
    nn/multilayer/MultiLayerNetwork.java:142."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 256 if _fast() else 2048
    iters = 5  # pretrain + finetune iterations per fit() call

    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).n_in(784).activation_function("sigmoid")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(iters)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(3)
            .hidden_layer_sizes([1024, 512])
            .override(0, layer="rbm", k=1)
            .override(1, layer="rbm", k=1)
            .override(2, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=10)
            .pretrain(True)
            .build())
    net = MultiLayerNetwork(conf)
    x_np, y_np = synthetic_mnist(batch_size)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    net.fit(x, y)  # compile every phase
    _d2h(net.params())
    # 12 fits keep the window >1 s now that the device-loop pretrain path
    # removed the per-optimize host syncs (short windows measure tunnel
    # weather, not throughput — see the GloVe spread history)
    fits = 1 if _fast() else 12

    def window():
        for _ in range(fits):
            net.fit(x, y)
        _d2h(net.params())

    processed = fits * batch_size * iters * 3
    rate, win_s = _median_rate(window, processed)
    return {"value": round(rate / max(1, len(jax.devices())), 2),
            "unit": "samples/sec/chip",
            "fits_per_window": fits, "window_s": round(win_s, 3)}


def _zipf_sentences(n_tokens, vocab_size, seed=0, sent_len=40):
    rng = np.random.RandomState(seed)
    vocab = [f"w{i}" for i in range(vocab_size)]
    zipf = 1.0 / np.arange(1, vocab_size + 1)
    probs = zipf / zipf.sum()
    tokens = rng.choice(vocab_size, size=n_tokens, p=probs)
    return [" ".join(vocab[t] for t in tokens[i:i + sent_len])
            for i in range(0, n_tokens, sent_len)]


def bench_word2vec():
    """BASELINE config 3 shape: Word2Vec skip-gram device-training
    throughput (pairs/sec) on a synthetic zipfian corpus. Pairs are
    mined ONCE up front and reused across all timed windows (mining
    throughput is a host property, reported separately as mine_s);
    training runs the production chunked-scan step. Reference path:
    nlp/models/word2vec/Word2Vec.java:101,
    InMemoryLookupTable.java:188."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    n_tokens = 20_000 if _fast() else 200_000
    w2v = Word2Vec(_zipf_sentences(n_tokens, 2000), layer_size=128,
                   window=5, min_word_frequency=1, negative=5,
                   iterations=1, seed=0)
    w2v.build_vocab()  # before the clock: mine_s times MINING only
    t0 = time.perf_counter()
    centers, contexts = w2v.mine_pairs(np.random.RandomState(1))
    mine_s = time.perf_counter() - t0
    B, CB = w2v.batch_pairs, w2v.chunk_batches
    if centers.size < B * CB:  # tiny corpus: tile up to one chunk
        reps = (B * CB) // centers.size + 1
        centers = np.tile(centers, reps)[:B * CB]
        contexts = np.tile(contexts, reps)[:B * CB]
    n = centers.size // (B * CB) * (B * CB)
    # upload ONCE; train_pairs passes device-resident arrays through
    import jax.numpy as jnp
    centers = jnp.asarray(centers[:n], jnp.int32)
    contexts = jnp.asarray(contexts[:n], jnp.int32)

    w2v.train_pairs(centers[:B * CB], contexts[:B * CB])  # compile
    _d2h(w2v.syn0)

    def window():
        w2v.train_pairs(centers, contexts)
        _d2h(w2v.syn0)

    rate, win_s = _median_rate(window, n)
    return {"value": round(rate, 2), "unit": "pairs/sec",
            "pairs_per_window": int(n), "mine_s": round(mine_s, 3),
            "window_s": round(win_s, 3)}


def bench_glove():
    """GloVe co-occurrence training throughput (triples/sec): corpus
    mined once via prepare(), timed windows run whole-epoch compiled
    scans. Reference path: nlp/models/glove/Glove.java:57-160."""
    from deeplearning4j_tpu.nlp.glove import Glove

    n_tokens = 20_000 if _fast() else 200_000
    glove = Glove(_zipf_sentences(n_tokens, 2000), layer_size=128,
                  window=5, min_word_frequency=1, batch_size=8192,
                  seed=0)
    t0 = time.perf_counter()
    glove.prepare()
    prep_s = time.perf_counter() - t0
    glove.train_epochs(1)  # compile (same per-epoch program all epochs)
    n = glove._triples[0].size
    B = glove.batch_size
    n_pad = (n + B - 1) // B * B
    # 16 epochs/window: with the round-5 device-side shuffle the
    # per-epoch H2D upload is gone and the per-call cost is the syn0
    # view refresh (~2 MB D2H) — longer windows amortize it so the pin
    # stops measuring tunnel bandwidth weather (old spread was ±35%)
    epochs = 1 if _fast() else 16

    def window():
        glove.train_epochs(epochs)  # train_epochs D2H-syncs (syn0 view)

    rate, win_s = _median_rate(window, epochs * n_pad)
    return {"value": round(rate, 2), "unit": "triples/sec",
            "triples": int(n), "prepare_s": round(prep_s, 3),
            "epochs_per_window": epochs, "window_s": round(win_s, 3)}


def bench_guardian():
    """Guardian robustness config (docs/FAULT_TOLERANCE.md): (a) guarded
    vs unguarded fit_scan step time — both driven as identical one-epoch
    compiled calls so the delta isolates the fused finite-check +
    where-commit (<2% target); (b) a NaN-injection recovery drill on the
    guarded iterator path — the poisoned batch must never commit
    (params finite) and the final score must land within 1e-3 of the
    fault-free run."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets import ListDataSetIterator
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.guardian import GuardianPolicy

    # ---- (a) guarded vs unguarded step time, chained on device
    net_u, batch_size = _mlp_net()
    net_g, _ = _mlp_net()
    n_batches, epochs = (4, 2) if _fast() else (16, 16)
    x_np, y_np = synthetic_mnist(batch_size * n_batches)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)
    # huge check/snapshot cadence: the window times the pure device-side
    # guard (the ladder's host syncs are per-check, amortized separately)
    policy = GuardianPolicy(check_every=10 ** 9, snapshot_every=10 ** 9)

    def one_pass(net, guarded):
        for _ in range(epochs):
            if guarded:
                net.fit_scan(x, y, batch_size=batch_size, epochs=1,
                             guardian=policy)
            else:
                net.fit_scan(x, y, batch_size=batch_size, epochs=1)
        _d2h(net.params())

    one_pass(net_u, False)  # compile
    one_pass(net_g, True)
    steps = n_batches * epochs
    rate_u, _ = _median_rate(lambda: one_pass(net_u, False), steps)
    rate_g, win_s = _median_rate(lambda: one_pass(net_g, True), steps)
    ms_u, ms_g = 1000.0 / rate_u, 1000.0 / rate_g
    overhead_pct = (ms_g - ms_u) / ms_u * 100.0

    # ---- (b) NaN-injection recovery drill (tiny net, guarded fit): ONE
    # transient fault in a long converging stream — the guarded run skips
    # the poisoned step and must land within 1e-3 of the clean run (the
    # skipped batch's influence decays once both runs sit in convergence)
    from deeplearning4j_tpu.datasets.iris import load_iris

    data = load_iris()
    ix, iy = np.asarray(data.features), np.asarray(data.labels)
    rng = np.random.RandomState(0)
    bs, n_steps = 24, 150
    sel = np.concatenate([rng.choice(len(ix), bs, replace=False)
                          for _ in range(n_steps)])
    dx, dy = ix[sel].copy(), iy[sel].copy()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False).momentum(0.5)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())

    clean = MultiLayerNetwork(conf)
    clean.fit(ListDataSetIterator(DataSet(dx, dy), bs))
    score_clean = clean.score(ix, iy)

    dx_bad = dx.copy()
    dx_bad[7 * bs:8 * bs] = np.nan  # one poisoned batch mid-stream
    faulty = MultiLayerNetwork(conf)
    faulty.fit(ListDataSetIterator(DataSet(dx_bad, dy), bs),
               guardian=GuardianPolicy(check_every=4, snapshot_every=16))
    params_finite = bool(np.isfinite(np.asarray(faulty.params())).all())
    score_faulty = faulty.score(ix, iy)
    delta = abs(score_faulty - score_clean)

    return {"value": round(ms_g, 4), "unit": "ms/guarded_step",
            "lower_is_better": True,
            "unguarded_ms": round(ms_u, 4),
            "overhead_pct": round(overhead_pct, 2),
            "recovery": {"params_finite": params_finite,
                         "score_clean": round(score_clean, 6),
                         "score_after_nan": round(score_faulty, 6),
                         "score_delta": round(delta, 6),
                         "recovered": bool(params_finite and delta < 1e-3)},
            "steps_per_window": steps, "window_s": round(win_s, 3)}


def bench_serve():
    """Serving config (docs/SERVING.md): (a) InferenceEngine throughput
    + p50/p99 latency over a synthetic RAGGED request stream — per-
    request eager dispatch is part of the metric (it is what serving
    pays per call), with the program-cache counter as the recompile
    guard; (b) transformer decode tokens/sec, KV-cache vs naive
    full-recompute — the cached path must win per token; (c)
    decode_concurrent: sustained DELIVERED tokens/sec under concurrent
    ragged EOS-terminated generate streams, continuous batching
    (DecodeLoop) vs the per-request generate_cached path — the >= 5x
    ROADMAP gate, with the decode-step program-cache counter proving
    one compiled program across all joins/leaves."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       generate,
                                                       init_transformer_params)
    from deeplearning4j_tpu.serving.engine import InferenceEngine

    fast = _fast()

    # ---- (a) ragged request stream through one engine
    net, _ = _mlp_net()
    max_batch = 64 if fast else 256
    engine = InferenceEngine.for_network(net, max_batch_size=max_batch)
    engine.warmup((784,))
    programs_after_warmup = engine.program_cache_size()
    rng = np.random.RandomState(0)
    n_requests = 24 if fast else 200
    sizes = rng.randint(1, max_batch + 1, size=n_requests)
    x_all, _ = synthetic_mnist(int(sizes.max()))
    requests = [x_all[:s] for s in sizes]
    total_rows = int(sizes.sum())

    def window():
        for req in requests:
            engine.infer(req)  # np.asarray inside = per-request D2H

    rows_rate, win_s = _median_rate(window, total_rows)
    programs = engine.program_cache_size()
    counters_ok = programs >= 0 and programs_after_warmup >= 0
    snap = engine.snapshot()

    # ---- (b) decode tokens/sec: KV cache vs naive full-recompute
    cfg = TransformerConfig(vocab_size=512, d_model=128, n_heads=4,
                            n_layers=2, d_ff=256,
                            max_len=64 if fast else 512,
                            interpret=fast)
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    b, t0 = 4, 16
    n_tok = (16 if fast else 128)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (b, t0)),
        jnp.int32)

    def decode_window(cache):
        def run():
            _d2h(generate(params, prompt, cfg, n_tok, cache=cache))
        run()  # compile
        rate, _ = _median_rate(run, b * n_tok)
        return rate

    tok_naive = decode_window(False)
    tok_cached = decode_window(True)

    # ---- (c) decode_concurrent: continuous batching vs per-request.
    # Chat-shaped workload: generous max_tokens caps, EOS-terminated
    # completions far shorter than the cap (each stream's EOS is a
    # token the model actually emits early, derived from its own greedy
    # reference). The per-request path CANNOT stop at EOS — n_tokens is
    # baked into its compiled signature — so it pays the full cap per
    # request, serially; the slot scheduler stops each stream at its
    # EOS and hands the freed slot to the next. Tokens/sec counts
    # DELIVERED (EOS-trimmed) tokens for both paths. Per-token compute
    # is identical by construction (parity-pinned), so the CPU-smoke
    # speedup isolates early-exit + admission batching; the TPU lane
    # adds batch-utilisation on top (a B=1 decode step starves the
    # chip).
    from deeplearning4j_tpu.serving.decode_loop import DecodeLoop
    from deeplearning4j_tpu.serving.kv_cache import (generate_cached,
                                                     kv_cache_bytes)
    from deeplearning4j_tpu.serving.paged_kv import pages_for_tokens

    ccfg = TransformerConfig(
        vocab_size=512, d_model=64 if fast else 256,
        n_heads=4, n_layers=2, d_ff=128 if fast else 512,
        max_len=128 if fast else 512, interpret=fast)
    cparams = init_transformer_params(jax.random.PRNGKey(0), ccfg)
    n_streams = 16 if fast else 32
    crng = np.random.RandomState(1)
    t0s = [int(crng.choice([8, 16]))
           for _ in range(n_streams)]
    cap_hi = ccfg.max_len * 3 // 4
    caps = [min(int(crng.choice([cap_hi * 2 // 3, cap_hi])),
                ccfg.max_len - t)
            for t in t0s]
    prompts = [crng.randint(0, ccfg.vocab_size, (t,)).astype(np.int32)
               for t in t0s]
    # greedy references double as the per-request compile warmup; the
    # EOS for each stream is a token its reference emits within the
    # first ~8 positions (clipped to the first occurrence)
    refs = [np.asarray(generate_cached(
                cparams, jnp.asarray(p[None]), ccfg, n))[0, t:].tolist()
            for p, n, t in zip(prompts, caps, t0s)]
    eos_ids, actuals = [], []
    for gen_toks in refs:
        tok = gen_toks[min(7, len(gen_toks) - 1)]
        eos_ids.append(tok)
        actuals.append(gen_toks.index(tok) + 1)
    useful = sum(actuals)

    def window_per_request():
        for p, n in zip(prompts, caps):
            np.asarray(generate_cached(cparams, jnp.asarray(p[None]),
                                       ccfg, n))

    seq_rate, seq_win = _median_rate(window_per_request, useful)

    loop = DecodeLoop(cparams, ccfg, slots=n_streams,
                      page_size=16, horizon=8)

    def window_continuous():
        streams = [loop.submit(p, n, eos_id=e)
                   for p, n, e in zip(prompts, caps, eos_ids)]
        for s in streams:
            s.result(240)

    window_continuous()  # warmup: compiles prefill buckets + the step
    step_programs_after_warmup = loop.decode_step_programs()
    cont_rate, cont_win = _median_rate(window_continuous, useful)
    csnap = loop.snapshot()
    step_programs = loop.decode_step_programs()
    counters_ok2 = (step_programs >= 0
                    and step_programs_after_warmup >= 0)
    # HBM accounting: the contiguous path reserves max_len per request;
    # the pool's peak holds only pages for tokens actually written
    contiguous_bytes = kv_cache_bytes(ccfg, 1) * n_streams
    page_bytes = csnap["pool_bytes"] // (csnap["pages_total"] + 1)
    peak_paged_bytes = csnap["peak_pages_in_use"] * page_bytes
    ideal_pages = sum(pages_for_tokens(t + a, 16)
                      for t, a in zip(t0s, actuals))
    # per-step KV traffic: the loop accounts BOTH lane figures every
    # dispatch (streamed-kernel pages vs the dense gather window), so
    # the reduction is visible whichever lane actually ran
    ckv = csnap["decode_kernel"]["kv_read_bytes"]
    loop.close()
    decode_concurrent = {
        "tokens_per_sec_continuous": round(cont_rate, 2),
        "tokens_per_sec_per_request": round(seq_rate, 2),
        "speedup": round(cont_rate / seq_rate, 2),
        "gate_5x": bool(cont_rate / seq_rate >= 5.0),
        "n_streams": n_streams,
        "useful_tokens": useful,
        "cap_tokens": sum(caps),
        "decode_step_programs":
            step_programs if counters_ok2 else None,
        "recompiled_after_warmup":
            (step_programs - step_programs_after_warmup)
            if counters_ok2 else None,
        "prefill_programs": csnap["prefill_programs"],
        "kv_hbm": {
            "contiguous_reservation_bytes": contiguous_bytes,
            "paged_pool_bytes": csnap["pool_bytes"],
            "peak_pages_in_use": csnap["peak_pages_in_use"],
            "peak_paged_bytes": peak_paged_bytes,
            "ideal_pages_for_written_tokens": ideal_pages,
            "paged_vs_contiguous":
                round(peak_paged_bytes / contiguous_bytes, 4),
        },
        "kv_read_per_step": {
            "path_selected": csnap["decode_kernel"]["selected"],
            "kernel_bytes": ckv["kernel"],
            "gather_bytes": ckv["gather"],
            "reduction": (round(ckv["gather"] / ckv["kernel"], 2)
                          if ckv["kernel"] else None),
        },
        "window_s": round(cont_win, 3),
        "per_request_window_s": round(seq_win, 3),
    }

    return {"value": round(tok_cached, 2), "unit": "tokens/sec_cached",
            "decode": {"tokens_per_sec_cached": round(tok_cached, 2),
                       "tokens_per_sec_naive": round(tok_naive, 2),
                       "cache_speedup": round(tok_cached / tok_naive, 2),
                       "batch": b, "prompt_len": t0, "n_tokens": n_tok},
            "decode_concurrent": decode_concurrent,
            "engine": {"rows_per_sec": round(rows_rate, 2),
                       "requests": n_requests,
                       "latency_p50_ms": snap["latency_p50_ms"],
                       "latency_p99_ms": snap["latency_p99_ms"],
                       "occupancy": round(snap["occupancy"], 4),
                       "compiled_programs":
                           programs if counters_ok else None,
                       "recompiled_after_warmup":
                           (programs - programs_after_warmup)
                           if counters_ok else None},
            "window_s": round(win_s, 3)}


def bench_prefix_cache():
    """Prefix-cache config (docs/SERVING.md "Prefix caching"). All
    numbers here are deterministic counters, not timings: the workload
    is token-for-token identical between a cache-OFF pass and a
    cache-ON pass, so the ratio of the loops' `prefill_tokens`
    counters IS the prefill work the cache removed — platform-
    independent and exactly reproducible. Three phases: (a) the
    shared-system-prompt drill — N requests share a page-aligned
    48-token head with short ragged tails, submitted sequentially so
    each retiree seeds the cache for its successors; gate >= 5x fewer
    real prefill tokens at bit-identical outputs, with ONE decode-step
    program and zero recompiles after warmup pinned across the whole
    run (admitting via cached pages must not mint new programs);
    (b) multi-turn replay — a conversation resubmits its own growing
    transcript each turn and the cache re-prefills only the new tail;
    (c) an end-to-end /metrics scrape off a live server, with a
    copy-on-write fork forced by replaying a fully cached prompt."""
    import urllib.request

    import jax

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_transformer_params)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.serving.decode_loop import DecodeLoop
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.server import serve_network

    fast = _fast()
    ps = 8
    cfg = TransformerConfig(vocab_size=512, d_model=64 if fast else 256,
                            n_heads=4, n_layers=2,
                            d_ff=128 if fast else 512,
                            max_len=128, interpret=fast)
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(7)
    head = rng.randint(0, cfg.vocab_size, (48,)).astype(np.int32)
    tails = [2, 3, 4, 5, 6, 4, 4, 4]  # ragged user turns, avg 4
    drill_prompts = [
        np.concatenate([head,
                        rng.randint(0, cfg.vocab_size, (t,)
                                    ).astype(np.int32)])
        for t in tails]
    turns = 4
    base = rng.randint(0, cfg.vocab_size, (16,)).astype(np.int32)
    turn_suffixes = [rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
                     for _ in range(turns - 1)]
    gen_tokens = 4

    def run_pass(enabled):
        loop = DecodeLoop(params, cfg, slots=4, page_size=ps,
                          horizon=4, prefix_cache=enabled)

        def gen(prompt):
            stream = loop.submit(np.asarray(prompt, np.int32),
                                 gen_tokens)
            return stream.full_sequence(240)

        outs, programs_after_first = [], None
        for p in drill_prompts:
            outs.append(gen(p))
            if programs_after_first is None:
                programs_after_first = loop.decode_step_programs()
        drill_prefill = loop.snapshot()["prefill_tokens"]
        convo, transcript = base.tolist(), []
        for t in range(turns):
            full = list(gen(convo))
            transcript.append(full)
            if t < turns - 1:
                convo = full + turn_suffixes[t].tolist()
        snap = loop.snapshot()
        loop.close()
        return {"outs": outs, "transcript": transcript,
                "drill_prefill": drill_prefill,
                "replay_prefill": snap["prefill_tokens"] - drill_prefill,
                "programs_after_first": programs_after_first,
                "snap": snap}

    cold = run_pass(False)
    warm = run_pass(True)

    identical = (cold["outs"] == warm["outs"]
                 and cold["transcript"] == warm["transcript"])
    reduction = cold["drill_prefill"] / max(1, warm["drill_prefill"])
    replay_reduction = (cold["replay_prefill"]
                        / max(1, warm["replay_prefill"]))
    step_programs = warm["snap"]["decode_step_programs"]
    counters_ok = (step_programs >= 0
                   and warm["programs_after_first"] >= 0)
    recompiled = step_programs - warm["programs_after_first"]
    pc = warm["snap"]["prefix_cache"]

    # ---- (c) e2e: the counters must be scrapeable off a live server.
    # Replaying a fully cached page-aligned prompt makes the first
    # decode write land in a shared page -> one copy-on-write fork.
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())
    gen_engine = InferenceEngine.for_transformer(params, cfg)
    prompt16 = [head[:16].tolist()]  # 2 full pages

    def post(url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def series(text, name):
        vals = [float(line.rsplit(" ", 1)[1])
                for line in text.splitlines() if line.startswith(name)]
        return sum(vals) if vals else -1.0

    with serve_network(MultiLayerNetwork(conf), n_replicas=1,
                       max_delay_ms=1.0, generate_engine=gen_engine,
                       slots=2, page_size=ps) as handle:
        first = post(f"{handle.url}/generate",
                     {"prompt": prompt16, "max_tokens": 4})
        replay = post(f"{handle.url}/generate",
                      {"prompt": prompt16, "max_tokens": 4})
        with urllib.request.urlopen(f"{handle.url}/metrics",
                                    timeout=30) as r:
            metrics_text = r.read().decode()
    hits_scraped = series(metrics_text, "dl4j_kv_prefix_hits_total")
    forks_scraped = series(metrics_text, "dl4j_kv_prefix_forks_total")
    scrape_ok = (replay["tokens"] == first["tokens"]
                 and hits_scraped >= 1.0 and forks_scraped >= 1.0)

    return {
        "value": round(reduction, 2),
        "unit": "x_prefill_token_reduction",
        "gate_5x": bool(identical and reduction >= 5.0),
        "outputs_identical": identical,
        "shared_prompt": {
            "requests": len(drill_prompts),
            "head_tokens": int(head.size),
            "page_size": ps,
            "prefill_tokens_cold": cold["drill_prefill"],
            "prefill_tokens_warm": warm["drill_prefill"],
            "reduction": round(reduction, 2),
        },
        "multi_turn": {
            "turns": turns,
            "prefill_tokens_cold": cold["replay_prefill"],
            "prefill_tokens_warm": warm["replay_prefill"],
            "reduction": round(replay_reduction, 2),
        },
        "prefix_cache": {"hits": pc["hits"], "misses": pc["misses"],
                         "forks": pc["forks"],
                         "evictions": pc["evictions"],
                         "pages_cached": pc["pages_cached"]},
        "decode_step_programs": step_programs if counters_ok else None,
        "recompiled_after_warmup": recompiled if counters_ok else None,
        "prefill_ctx_programs": warm["snap"]["prefill_ctx_programs"],
        "metrics_scrape": {"hits_total": hits_scraped,
                           "forks_total": forks_scraped,
                           "replay_bit_identical":
                               replay["tokens"] == first["tokens"],
                           "ok": scrape_ok},
    }


def bench_speculative():
    """Speculative-decoding config (docs/SERVING.md "Speculative
    decoding"): the chat-replay drill — templated prompts (shared
    system head + short user tails) whose greedy continuations recur —
    decoded plain vs draft-and-verify with BOTH drafter flavors at
    BIT-IDENTICAL output. The gated metric is deterministic and
    platform-independent: delivered tokens per TARGET-model dispatch
    (the weight sweep speculation amortizes), which must be >= 2x the
    plain lane's for both flavors. Wall tokens/sec is reported for
    both lanes but only meaningful where the step is bandwidth/
    dispatch-bound (the TPU lane); the CPU smoke is compute-bound, so
    a widened verify costs ~W forwards and wall speedup < 1 there by
    construction. The model flavor runs a draft DISTILLED on the
    target's own greedy traffic (drafter-shaped right-aligned windows
    — the positions the drafter actually sees), the pairing a real
    deployment ships; acceptance rates for both flavors are also
    scraped END TO END off a live /metrics."""
    import urllib.request

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, init_transformer_params, transformer_logits)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.serving.decode_loop import DecodeLoop
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.kv_cache import generate_cached
    from deeplearning4j_tpu.serving.server import serve_network

    fast = _fast()
    cfg = TransformerConfig(vocab_size=512, d_model=64 if fast else 256,
                            n_heads=4, n_layers=2 if fast else 4,
                            d_ff=128 if fast else 512,
                            max_len=128 if fast else 512,
                            interpret=fast)
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    dcfg = TransformerConfig(vocab_size=512, d_model=32 if fast else 64,
                             n_heads=2, n_layers=1,
                             d_ff=64 if fast else 128,
                             max_len=cfg.max_len, interpret=fast)
    spec_k, draft_win = 4, 32
    n_streams, cap = 8, 48
    rng = np.random.RandomState(1)
    system = rng.randint(0, cfg.vocab_size, (12,)).astype(np.int32)
    prompts = [np.concatenate(
        [system, rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)])
        for _ in range(n_streams)]

    # ---- distill the draft on the target's own greedy rollouts of
    # this traffic, sample-shaped exactly like drafter inference:
    # right-aligned zero-padded windows predicting the next token
    seqs = np.asarray(generate_cached(
        params, jnp.asarray(np.stack(prompts)), cfg, cap))
    wins, labels = [], []
    for s in seqs:
        for cut in range(4, len(s)):
            w = np.zeros((draft_win,), np.int32)
            h = s[max(0, cut - draft_win):cut]
            w[draft_win - len(h):] = h
            wins.append(w)
            labels.append(s[cut])
    wins = np.stack(wins)
    labels = np.asarray(labels, np.int32)

    def distill_loss(p, w, y):
        logits = transformer_logits(p, w, dcfg)[:, -1, :]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

    @jax.jit
    def distill_step(p, m, v, i, w, y):
        g = jax.grad(distill_loss)(p, w, y)
        b1, b2, lr, eps = 0.9, 0.999, 3e-3, 1e-8
        m = jax.tree_util.tree_map(lambda a, b: b1 * a + (1 - b1) * b,
                                   m, g)
        v = jax.tree_util.tree_map(
            lambda a, b: b2 * a + (1 - b2) * b * b, v, g)

        def upd(p_, m_, v_):
            return p_ - lr * (m_ / (1 - b1 ** i)) / (
                jnp.sqrt(v_ / (1 - b2 ** i)) + eps)

        return jax.tree_util.tree_map(upd, p, m, v), m, v

    dparams = init_transformer_params(jax.random.PRNGKey(7), dcfg)
    m = jax.tree_util.tree_map(jnp.zeros_like, dparams)
    v = jax.tree_util.tree_map(jnp.zeros_like, dparams)
    t_distill = time.perf_counter()
    wj, yj = jnp.asarray(wins), jnp.asarray(labels)
    for i in range(1, 401):
        idx = np.random.RandomState(i).randint(0, len(wins), (64,))
        dparams, m, v = distill_step(dparams, m, v, jnp.float32(i),
                                     wj[idx], yj[idx])
    dparams = jax.tree_util.tree_map(np.asarray, dparams)
    distill_s = time.perf_counter() - t_distill

    # ---- the three lanes over the identical replayed workload
    def run_lane(**kw):
        loop = DecodeLoop(params, cfg, slots=n_streams, page_size=16,
                          **kw)

        def window():
            streams = [loop.submit(list(p), cap) for p in prompts]
            for s in streams:
                s.result(240)
            return [s.full_sequence(1) for s in streams]

        outs = window()  # warmup: compiles + seeds the replay corpus
        if kw.get("speculation"):
            # the width-1 fallback chain is part of the speculative
            # lane (rounds where nothing drafts run it) — warm it too
            # so the recompile guard pins BOTH programs
            loop.submit(list(prompts[0]), 2,
                        speculation=False).result(240)
        programs_warm = loop.decode_step_programs()
        d0 = loop.snapshot()["dispatches"]
        rate, win_s = _median_rate(window, n_streams * cap)
        snap = loop.snapshot()
        dispatches = (snap["dispatches"] - d0) / REPEATS
        programs = loop.decode_step_programs()
        spec = snap["speculation"]
        loop.close()
        return outs, {
            "tokens_per_sec": round(rate, 2),
            "tokens_per_dispatch":
                round(n_streams * cap / dispatches, 2),
            "dispatches_per_window": round(dispatches, 1),
            "acceptance_rate": round(spec["acceptance_rate"], 4),
            "proposed": spec["proposed"],
            "accepted": spec["accepted"],
            "decode_step_programs":
                programs if programs >= 0 else None,
            "recompiled_after_warmup":
                (programs - programs_warm) if programs >= 0
                and programs_warm >= 0 else None,
            "window_s": round(win_s, 3),
        }

    ref, plain = run_lane()
    out_ng, ngram = run_lane(speculation=spec_k, drafter="ngram")
    out_md, model = run_lane(speculation=spec_k, drafter="model",
                             draft_params=dparams, draft_cfg=dcfg,
                             draft_window=draft_win)
    identical = ref == out_ng == out_md
    for lane, res in (("ngram", ngram), ("model", model)):
        res["speedup_tokens_per_dispatch"] = round(
            res["tokens_per_dispatch"] / plain["tokens_per_dispatch"],
            2)
        res["speedup_wall"] = round(
            res["tokens_per_sec"] / plain["tokens_per_sec"], 2)
    gate = bool(identical
                and ngram["speedup_tokens_per_dispatch"] >= 2.0
                and model["speedup_tokens_per_dispatch"] >= 2.0
                and ngram["recompiled_after_warmup"] == 0
                and model["recompiled_after_warmup"] == 0
                and (ngram["decode_step_programs"] or 0) <= 2
                and (model["decode_step_programs"] or 0) <= 2)

    # ---- e2e: acceptance rate scraped off a LIVE /metrics
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())

    def post(url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def series(text, name, agg):
        # the registry is process-global: earlier lanes in THIS run
        # left their (zeroed, closed-loop) series behind, so aggregate
        # across labels instead of trusting line order
        vals = [float(line.rsplit(" ", 1)[1])
                for line in text.splitlines() if line.startswith(name)]
        return agg(vals) if vals else -1.0

    gen_engine = InferenceEngine.for_transformer(params, cfg)
    with serve_network(MultiLayerNetwork(conf), n_replicas=1,
                       max_delay_ms=1.0, generate_engine=gen_engine,
                       slots=4, page_size=16, speculation=spec_k,
                       drafter="model", draft_params=dparams,
                       draft_cfg=dcfg,
                       draft_window=draft_win) as handle:
        first = post(f"{handle.url}/generate",
                     {"prompt": [prompts[0].tolist()],
                      "max_tokens": cap})
        replay = post(f"{handle.url}/generate",
                      {"prompt": [prompts[0].tolist()],
                       "max_tokens": cap})
        with urllib.request.urlopen(f"{handle.url}/metrics",
                                    timeout=30) as r:
            metrics_text = r.read().decode()
        with urllib.request.urlopen(f"{handle.url}/stats",
                                    timeout=30) as r:
            spec_live = json.loads(r.read())[
                "generate"]["decode"]["speculation"]
    # dead bench-lane loops above still expose zeroed gauge lines;
    # max picks the live serving loop's
    rate_scraped = series(metrics_text, "dl4j_spec_acceptance_rate",
                          max)
    scrape_ok = (replay["tokens"] == first["tokens"]
                 and "dl4j_spec_proposed" in metrics_text
                 and "dl4j_spec_rounds" in metrics_text
                 and spec_live["proposed"] >= 1
                 and 0.0 < rate_scraped <= 1.0
                 and abs(rate_scraped - spec_live["acceptance_rate"])
                 < 1e-6)

    return {
        "value": ngram["speedup_tokens_per_dispatch"],
        "unit": "x_tokens_per_target_dispatch",
        "gate_2x": gate,
        "outputs_identical": identical,
        "spec_k": spec_k,
        "workload": {"n_streams": n_streams, "max_tokens": cap,
                     "system_head_tokens": int(system.size),
                     "replayed_windows": REPEATS + 1},
        "plain": plain,
        "ngram": ngram,
        "model": dict(model, distill_s=round(distill_s, 1),
                      distill_pairs=len(wins)),
        "metrics_scrape": {
            "acceptance_rate": rate_scraped,
            "proposed_total": spec_live["proposed"],
            "replay_bit_identical": replay["tokens"] == first["tokens"],
            "ok": scrape_ok},
    }


def bench_fleet():
    """Fleet config (docs/FLEET.md): (a) scaling curve — aggregate
    /predict rows/sec and client-side p99 through the router over 1 ->
    2 -> 4 local replica PROCESSES (each a spawned `cli serve`; on the
    1-core CPU smoke the curve is flat by construction — the record is
    the router overhead and the harness, the TPU lane is where the
    fan-out pays); (b) availability drill: kill one of two replicas
    mid-hammer — the gate is ZERO client errors (idempotent retries on
    the surviving replica) and bounded p99 degradation."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving.fleet import Fleet, ReplicaSpawner
    from deeplearning4j_tpu.serving.router import serve_fleet

    fast = _fast()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(16).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([32])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=4)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_fleet_")
    ckpt = os.path.join(work, "fleet.ckpt")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    spawner = ReplicaSpawner(ckpt, serve_args=["--max-delay-ms", "1"])

    rows = 4
    body = _json.dumps(
        {"inputs": np.random.RandomState(0).rand(rows, 16).tolist()}
    ).encode()

    def hammer(url, n_threads, per_thread):
        """Concurrent client load; returns (latencies_s, errors)."""
        lats, errors = [], []
        lock = threading.Lock()

        def worker():
            for _ in range(per_thread):
                t0 = time.perf_counter()
                try:
                    req = urllib.request.Request(
                        url + "/predict", data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=60) as r:
                        r.read()
                    dt = time.perf_counter() - t0
                    with lock:
                        lats.append(dt)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(n_threads)]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lats, errors, time.perf_counter() - start

    def p99(lats):
        return (sorted(lats)[max(0, int(len(lats) * 0.99) - 1)]
                if lats else None)

    n_threads = 4
    per_thread = 16 if fast else 64
    scaling = {}
    drill = None
    try:
        for n in (1, 2, 4):
            fleet = Fleet(spawner=spawner, heartbeat_interval=0.2,
                          heartbeat_timeout=2.0)
            router = None
            try:
                fleet.spawn(n)
                fleet.wait_ready(n, timeout=240)
                router = serve_fleet(fleet)
                hammer(router.url, n_threads, 4)  # warm every replica
                lats, errors, wall = hammer(router.url, n_threads,
                                            per_thread)
                sp99 = p99(lats)
                scaling[str(n)] = {
                    "rows_per_sec": round(len(lats) * rows / wall, 2),
                    "p99_ms": round(sp99 * 1e3, 2) if sp99 else None,
                    "requests": len(lats),
                    "errors": len(errors),
                }
                if n == 2:
                    # ---- availability drill on this rung: kill one
                    # replica under load, count client-visible errors
                    calm_p99 = p99(lats)
                    victim = next(iter(fleet._replicas.values()))
                    stop = threading.Event()
                    drill_lats, drill_errors = [], []
                    dlock = threading.Lock()

                    def drill_worker():
                        while not stop.is_set():
                            t0 = time.perf_counter()
                            try:
                                req = urllib.request.Request(
                                    router.url + "/predict", data=body,
                                    headers={"Content-Type":
                                             "application/json"})
                                with urllib.request.urlopen(
                                        req, timeout=60) as r:
                                    r.read()
                                with dlock:
                                    drill_lats.append(
                                        time.perf_counter() - t0)
                            except Exception as e:  # noqa: BLE001
                                with dlock:
                                    drill_errors.append(repr(e))

                    workers = [threading.Thread(target=drill_worker,
                                                daemon=True)
                               for _ in range(n_threads)]
                    for t in workers:
                        t.start()
                    time.sleep(0.4)
                    victim.proc.kill()
                    killed_at = time.monotonic()
                    evicted_in = None
                    while time.monotonic() - killed_at < 10.0:
                        if victim.state == "evicted":
                            evicted_in = time.monotonic() - killed_at
                            break
                        time.sleep(0.02)
                    time.sleep(0.8)  # keep hammering the survivor
                    stop.set()
                    for t in workers:
                        t.join(timeout=60)
                    dp99 = p99(drill_lats)
                    bound = max(20 * calm_p99, 5.0)
                    snap = fleet.snapshot()
                    drill = {
                        "errors": len(drill_errors),
                        "requests": len(drill_lats),
                        "p99_ms": round(dp99 * 1e3, 2) if dp99 else None,
                        "calm_p99_ms": round(calm_p99 * 1e3, 2),
                        "p99_bound_ms": round(bound * 1e3, 2),
                        "evicted_in_s": (round(evicted_in, 3)
                                         if evicted_in else None),
                        "retries": snap["retries"],
                        "gate_zero_errors": len(drill_errors) == 0,
                        "gate_p99_bounded": bool(dp99 and dp99 <= bound),
                    }
            finally:
                if router is not None:
                    router.close(stop_replicas=True)
                else:
                    fleet.close(stop_replicas=True)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    top = scaling[str(max(int(k) for k in scaling))]
    return {"value": top["rows_per_sec"], "unit": "rows/sec",
            "replicas_at_value": max(int(k) for k in scaling),
            "scaling": scaling,
            "availability_drill": drill,
            "threads": n_threads, "rows_per_request": rows}


def bench_chaos():
    """Chaos availability drill (ISSUE 8, docs/FLEET.md "Chaos
    runbook"): SIGSTOP one of two replica processes mid-hammer — hung,
    NOT dead: the kernel keeps accepting connections into the listen
    backlog, so connection-failure eviction never fires and only the
    request path stalls. Every client request carries an
    `X-Deadline-Ms` budget. Gates: ZERO client-visible failures within
    those budgets (per-hop deadline-derived timeouts + retries on the
    healthy peer absorb every stall), the circuit breaker evicts the
    hung member within 2x its detection window (breaker_threshold x
    request_timeout + breaker_reset_s — the heartbeat path cannot see
    this failure mode), bounded p99 degradation, and SIGCONT leads to
    half-open `/readyz` readmission."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving.fleet import (Fleet, ReplicaSpawner,
                                                  EVICTED, READY)
    from deeplearning4j_tpu.serving.router import serve_fleet
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    fast = _fast()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(16).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([32])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=4)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_chaos_")
    ckpt = os.path.join(work, "chaos.ckpt")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    spawner = ReplicaSpawner(ckpt, serve_args=["--max-delay-ms", "1"])

    rows = 4
    deadline_ms = 20_000
    body = _json.dumps(
        {"inputs": np.random.RandomState(0).rand(rows, 16).tolist()}
    ).encode()
    request_timeout, breaker_threshold, breaker_reset_s = 0.5, 2, 0.4
    # the breaker's detection window: enough consecutive timeouts to
    # reach the threshold, plus the open -> half-open wait
    detection_s = breaker_threshold * request_timeout + breaker_reset_s

    def p99(lats):
        return (sorted(lats)[max(0, int(len(lats) * 0.99) - 1)]
                if lats else None)

    fleet = Fleet(spawner=spawner, heartbeat_interval=0.2,
                  heartbeat_timeout=3.0,
                  request_timeout=request_timeout,
                  retry_budget=2,
                  breaker_threshold=breaker_threshold,
                  breaker_reset_s=breaker_reset_s)
    router = None
    try:
        fleet.spawn(2)
        fleet.wait_ready(2, timeout=240)
        router = serve_fleet(fleet)

        lats, errors = [], []
        lock = threading.Lock()
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    req = urllib.request.Request(
                        router.url + "/predict", data=body,
                        headers={"Content-Type": "application/json",
                                 "X-Deadline-Ms": str(deadline_ms)})
                    with urllib.request.urlopen(
                            req, timeout=deadline_ms / 1e3) as r:
                        r.read()
                    with lock:
                        lats.append(time.perf_counter() - t0)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))

        n_threads = 4
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        warm_s = 0.5 if fast else 1.5
        time.sleep(warm_s)              # calm traffic through both
        with lock:
            calm_lats, calm_n = list(lats), len(lats)
        calm_p99 = p99(calm_lats)

        victim = next(iter(fleet._replicas.values()))
        chaos_mod.sigstop(victim.proc)  # hung-but-TCP-alive
        stopped_at = time.monotonic()
        evicted_in = None
        while time.monotonic() - stopped_at < 30.0:
            if victim.state == EVICTED:
                evicted_in = time.monotonic() - stopped_at
                break
            time.sleep(0.02)
        time.sleep(0.5 if fast else 1.0)  # hammer the survivor
        chaos_mod.sigcont(victim.proc)    # recovery half of the drill
        cont_at = time.monotonic()
        readmitted_in = None
        while time.monotonic() - cont_at < 30.0:
            if victim.state == READY:
                readmitted_in = time.monotonic() - cont_at
                break
            time.sleep(0.05)
        time.sleep(0.3)                   # traffic over the full fleet
        stop.set()
        for t in threads:
            t.join(timeout=60)

        with lock:
            drill_lats = lats[calm_n:]
            n_errors = len(errors)
            err_sample = errors[:3]
        dp99 = p99(drill_lats)
        bound = max(20 * calm_p99, 5.0) if calm_p99 else 5.0
        snap = fleet.snapshot()
        return {
            "value": round(evicted_in, 3) if evicted_in else None,
            "unit": "s_to_breaker_eviction",
            "lower_is_better": True,
            "requests": len(drill_lats) + calm_n,
            "errors": n_errors,
            "error_sample": err_sample,
            "deadline_ms": deadline_ms,
            "calm_p99_ms": (round(calm_p99 * 1e3, 2)
                            if calm_p99 else None),
            "drill_p99_ms": round(dp99 * 1e3, 2) if dp99 else None,
            "p99_bound_ms": round(bound * 1e3, 2),
            "eviction_reason": victim.eviction_reason,
            "breaker_detection_window_s": detection_s,
            "evicted_in_s": (round(evicted_in, 3)
                             if evicted_in else None),
            "readmitted_in_s": (round(readmitted_in, 3)
                                if readmitted_in else None),
            "request_timeouts": snap["request_timeouts"],
            "breaker_opens": snap["breaker_opens"],
            "retries": snap["retries"],
            "gate_zero_errors_within_deadline": n_errors == 0,
            "gate_breaker_eviction_bounded": bool(
                evicted_in is not None
                and evicted_in <= 2.0 * detection_s),
            "gate_p99_bounded": bool(dp99 and dp99 <= bound),
            "gate_half_open_readmission": readmitted_in is not None,
        }
    finally:
        if router is not None:
            router.close(stop_replicas=True)
        else:
            fleet.close(stop_replicas=True)
        shutil.rmtree(work, ignore_errors=True)


def bench_stream_failover():
    """Durable-stream failover drill (ISSUE 15, docs/FLEET.md "Stream
    failover"): SIGKILL one of two replica processes while concurrent
    /generate streams are mid-flight. The replicas serve a
    deterministically-initialized transformer (`--transformer SPEC`),
    so the router's resume — replaying `prompt + delivered` on the
    survivor — must produce a continuation BIT-IDENTICAL to an
    uninterrupted reference. Gates: ZERO client-visible stream
    failures (every stream gapless, duplicate-free, token-for-token
    equal to the reference), replayed-prefill tokens bounded by
    prompt+generated per resumed stream (and the survivor's warm
    prefix cache absorbs the replayed prompt page), and bounded p99
    time-to-next-token across the hop."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving.fleet import Fleet, ReplicaSpawner
    from deeplearning4j_tpu.serving.router import serve_fleet
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    fast = _fast()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_failover_")
    ckpt = os.path.join(work, "failover.ckpt")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    spec = os.path.join(work, "tf.json")
    with open(spec, "w") as f:
        _json.dump({"vocab_size": 17, "d_model": 32, "n_heads": 2,
                    "n_layers": 2, "d_ff": 64, "max_len": 64,
                    "interpret": fast,  # pallas interpreter off-TPU
                    "seed": 0}, f)
    # pace token emission so the SIGKILL lands MID-stream
    delay_s = 0.02 if fast else 0.03
    env = dict(os.environ,
               **chaos_mod.env_spec([chaos_mod.Rule(
                   "generate.midstream", "delay", delay_s=delay_s)]))
    spawner = ReplicaSpawner(
        ckpt, serve_args=["--max-delay-ms", "1", "--transformer", spec,
                          "--slots", "8", "--page-size", "8"],
        env=env)

    # prompt fills exactly one KV page: the warm passes seed it into
    # each replica's prefix cache, so a resumed replay's prefill is a
    # cache hit instead of recompute
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    n_tokens = 16 if fast else 32
    n_streams = 4
    body = _json.dumps({"prompt": [prompt], "max_tokens": n_tokens,
                        "stream": True}).encode()

    def run_stream(out_events, out_times):
        req = urllib.request.Request(
            f"{router.url}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            for ln in r:
                if not ln.strip():
                    continue
                out_events.append(_json.loads(ln))
                out_times.append(time.perf_counter())

    def p99(gaps):
        return (sorted(gaps)[max(0, int(len(gaps) * 0.99) - 1)]
                if gaps else None)

    fleet = Fleet(spawner=spawner, heartbeat_interval=0.2,
                  heartbeat_timeout=3.0, breaker_threshold=2,
                  breaker_reset_s=0.4)
    router = None
    try:
        fleet.spawn(2)
        fleet.wait_ready(2, timeout=300)
        router = serve_fleet(fleet)

        # warm passes: compile the decode path AND seed the prompt's
        # page into both replicas' prefix caches (sequential requests
        # round-robin across the pair)
        ref_toks = None
        calm_gaps = []
        for _ in range(2):
            ev, ts = [], []
            run_stream(ev, ts)
            toks = [e["token"] for e in ev if "token" in e]
            assert len(toks) == n_tokens
            if ref_toks is None:
                ref_toks = toks
            assert toks == ref_toks
            calm_gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        calm_p99 = p99(calm_gaps)

        # drill: concurrent streams, SIGKILL the busy replica mid-flight
        all_events = [[] for _ in range(n_streams)]
        all_times = [[] for _ in range(n_streams)]
        errors = []

        def worker(i):
            try:
                run_stream(all_events[i], all_times[i])
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True)
                   for i in range(n_streams)]
        for t in threads:
            t.start()
        victim = None
        kill_by = time.monotonic() + 30.0
        while victim is None and time.monotonic() < kill_by:
            busy = [r for r in fleet._replicas.values()
                    if r.outstanding]
            victim = busy[0] if busy else None
            time.sleep(0.01)
        time.sleep(6 * delay_s)          # a few tokens in flight
        chaos_mod.sigkill(victim.proc)
        for t in threads:
            t.join(timeout=300)

        # exactly-once + bit-identical across every stream
        failures = list(errors)
        resumes = 0
        drill_gaps = []
        for ev, ts in zip(all_events, all_times):
            toks = [e for e in ev if "token" in e]
            if [e["token_index"] for e in toks] != list(range(n_tokens)):
                failures.append("token_index gap/dup")
            if [e["token"] for e in toks] != ref_toks:
                failures.append("tokens diverged from reference")
            if not (ev and ev[-1].get("done")):
                failures.append("stream ended without done")
            else:
                resumes += ev[-1]["resumes"]
            drill_gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        dp99 = p99(drill_gaps)
        bound = max(20 * calm_p99, 5.0) if calm_p99 else 5.0

        snap = fleet.snapshot()
        survivor = next(r for r in fleet._replicas.values()
                        if r.id != victim.id)
        sdec = survivor.client.stats()["generate"]["decode"]
        # replay budget: each resumed stream replays at most its
        # prompt + everything generated so far
        replay_budget = n_streams * (len(prompt) + n_tokens)
        return {
            "value": round(dp99 * 1e3, 2) if dp99 else None,
            "unit": "p99_time_to_next_token_ms",
            "lower_is_better": True,
            "streams": n_streams,
            "tokens_per_stream": n_tokens,
            "stream_failures": len(failures),
            "failure_sample": failures[:3],
            "resumes": resumes,
            "fleet_stream_resumes": snap["stream_resumes"],
            "tokens_replayed": snap["stream_tokens_replayed"],
            "tokens_deduped": snap["stream_tokens_deduped"],
            "replay_budget_tokens": replay_budget,
            "survivor_prefix_hits": sdec["prefix_cache"]["hits"],
            "survivor_decode_programs": sdec["decode_step_programs"],
            "calm_p99_ttnt_ms": (round(calm_p99 * 1e3, 2)
                                 if calm_p99 else None),
            "drill_p99_ttnt_ms": (round(dp99 * 1e3, 2)
                                  if dp99 else None),
            "p99_bound_ms": round(bound * 1e3, 2),
            "gate_zero_stream_failures": not failures,
            "gate_resumed": snap["stream_resumes"] >= 1,
            "gate_replay_bounded": (
                0 < snap["stream_tokens_replayed"] <= replay_budget),
            "gate_warm_replay_prefix_hits":
                sdec["prefix_cache"]["hits"] >= 1,
            "gate_p99_ttnt_bounded": bool(dp99 and dp99 <= bound),
            "gate_one_decode_program":
                sdec["decode_step_programs"] == 1,
        }
    finally:
        if router is not None:
            router.close(stop_replicas=True)
        else:
            fleet.close(stop_replicas=True)
        shutil.rmtree(work, ignore_errors=True)


def bench_fleet_prefix():
    """Fleet KV plane drill (docs/FLEET.md "Fleet KV plane"): a
    fleet of 4 replica processes serving one shared system prompt
    with per-request tails — the chat-shaped traffic the plane
    exists for. Two phases over the SAME warm fleet (distinct
    system prompts per phase, so neither inherits the other's
    caches):

    - fleet_kv=off router: round-robin sprays the shared head
      across the fleet, every replica pays its own cold prefill —
      the single-replica cache's fleet-wide reduction collapses.
    - fleet_kv=on router: prefix affinity converges the head onto
      one replica (tail-only prefill from request 2 on), and under
      a concurrent hammer the slack-bounded spill ships the hot
      pages peer-to-peer instead of recomputing them.

    Gates: fleet-wide prefill-token reduction >= 4x with affinity
    (and strictly above the off-mode figure), zero client-visible
    stream failures with the AFFINITY HOLDER SIGKILLed mid-hammer,
    p99 no worse than the same hammer+kill without affinity (a dead
    preferred replica must not convoy), >= 1 real page ship, and
    `dl4j_fleet_prefix_{affinity_hits,page_ships}` scraped live off
    the router's /metrics."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving import fleetkv
    from deeplearning4j_tpu.serving.fleet import READY, Fleet, ReplicaSpawner
    from deeplearning4j_tpu.serving.router import serve_fleet
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    fast = _fast()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_fleetkv_")
    ckpt = os.path.join(work, "fleetkv.ckpt")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    spec = os.path.join(work, "tf.json")
    with open(spec, "w") as f:
        _json.dump({"vocab_size": 17, "d_model": 32, "n_heads": 2,
                    "n_layers": 2, "d_ff": 64, "max_len": 96,
                    "interpret": fast, "seed": 0}, f)
    # pace token emission so both phases' SIGKILLs land MID-stream —
    # without it the hammer streams finish before the kill and the
    # p99 comparison is a control in name only
    delay_s = 0.03
    env = dict(os.environ,
               **chaos_mod.env_spec([chaos_mod.Rule(
                   "generate.midstream", "delay", delay_s=delay_s)]))
    # one shared CPU core: donors answer /kv/export while decoding, so
    # give ships headroom over the 2 s production default — expiry
    # would silently fall back to plain prefill and starve the drill
    spawner = ReplicaSpawner(
        ckpt, serve_args=["--max-delay-ms", "1", "--transformer", spec,
                          "--slots", "8", "--page-size", "8",
                          "--kv-pages", "64", "--fleet-kv", "on",
                          "--kv-ship-timeout", "10"],
        env=env)

    n_fleet = 4
    # shared system prompt = 5 full KV pages, per-request tail = 1:
    # with affinity every request after the first prefills only its
    # tail, so the fleet-wide reduction approaches 6x (48/8) while
    # round-robin re-pays the head once per replica
    head_len, tail_len = 40, 8
    n_tokens = 4          # calm phase: measure prefill, not decode
    n_hammer_tokens = 24  # hammer: long enough to be killed mid-flight
    n_calm = 16 if fast else 24
    n_hammer = 8 if fast else 16

    def prompts_for(seed):
        rng = np.random.RandomState(seed)
        head = rng.randint(1, 17, (head_len,)).tolist()
        return [head + rng.randint(1, 17, (tail_len,)).tolist()
                for _ in range(max(n_calm, n_hammer))]

    def post(url, payload, timeout=300):
        req = urllib.request.Request(
            url, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return _json.loads(r.read())

    def fleet_prefill():
        total = 0
        for r in fleet._replicas.values():
            if r.state != READY:
                continue
            try:
                total += (r.client.stats()["generate"]["decode"]
                          ["prefill_tokens"])
            except Exception:
                pass
        return total

    def calm_phase(router, prompts):
        """Sequential requests; returns (reduction, latencies)."""
        before = fleet_prefill()
        lats = []
        for pr in prompts[:n_calm]:
            t0 = time.perf_counter()
            post(f"{router.url}/generate",
                 {"prompt": [pr], "max_tokens": n_tokens})
            lats.append(time.perf_counter() - t0)
        submitted = sum(len(p) for p in prompts[:n_calm])
        measured = max(1, fleet_prefill() - before)
        return submitted / measured, lats

    def hammer_phase(router, prompts, wait_ships=False):
        """Concurrent durable streams + SIGKILL mid-drill. The victim
        is the busiest replica — with affinity on that IS the
        prefix holder/donor, so the drill proves a dead preferred
        replica cannot convoy routing. Streams launch in two waves:
        the first fills the preferred replica past PLACEMENT_SLACK so
        the second wave demonstrably spills (off-donor landings ->
        donor hints -> page ships); with `wait_ships` the kill holds
        until the fleet counters show a ship landed — the donor dies
        AFTER proving the plane works, while its streams are still
        mid-flight."""
        lats, errors, resumes = [], [], [0]

        def worker(i):
            body = {"prompt": [prompts[i % len(prompts)]],
                    "max_tokens": n_hammer_tokens, "stream": True}
            try:
                t0 = time.perf_counter()
                req = urllib.request.Request(
                    f"{router.url}/generate",
                    data=_json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                events = []
                with urllib.request.urlopen(req, timeout=300) as r:
                    for ln in r:
                        if ln.strip():
                            events.append(_json.loads(ln))
                lats.append(time.perf_counter() - t0)
                toks = [e for e in events if "token" in e]
                if not (events and events[-1].get("done")
                        and len(toks) == n_hammer_tokens):
                    errors.append(
                        f"stream {i}: bad terminal "
                        f"({len(toks)}/{n_hammer_tokens} tokens)")
                else:
                    resumes[0] += events[-1].get("resumes", 0)
            except Exception as e:  # noqa: BLE001
                errors.append(f"stream {i}: {e!r}")

        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True)
                   for i in range(n_hammer)]
        wave1 = fleetkv.PLACEMENT_SLACK + 1  # fills the preference
        for t in threads[:wave1]:
            t.start()
        time.sleep(0.4)
        for t in threads[wave1:]:            # these spill (and ship)
            t.start()
        if wait_ships:
            ship_by = time.monotonic() + 8.0
            while time.monotonic() < ship_by:
                if fleet.snapshot()["prefix_cache"]["page_ships"] >= 1:
                    break
                time.sleep(0.05)
        victim = None
        kill_by = time.monotonic() + 30.0
        while victim is None and time.monotonic() < kill_by:
            busy = sorted((r for r in fleet._replicas.values()
                           if r.outstanding and r.proc is not None),
                          key=lambda r: -r.outstanding)
            victim = busy[0] if busy else None
            time.sleep(0.01)
        if victim is not None:
            time.sleep(6 * delay_s)  # a few tokens in flight
            chaos_mod.sigkill(victim.proc)
        for t in threads:
            t.join(timeout=300)
        return lats, errors, resumes[0]

    def p99(xs):
        return (sorted(xs)[max(0, int(len(xs) * 0.99) - 1)]
                if xs else None)

    fleet = Fleet(spawner=spawner, heartbeat_interval=0.2,
                  heartbeat_timeout=3.0, breaker_threshold=2,
                  breaker_reset_s=0.4)
    router = None
    try:
        fleet.spawn(n_fleet)
        fleet.wait_ready(n_fleet, timeout=600)

        # ---- phase OFF: same fleet, affinity-blind router
        router = serve_fleet(fleet, fleet_kv="off")
        off_reduction, _ = calm_phase(router, prompts_for(1))
        off_lats, off_errs, _ = hammer_phase(router, prompts_for(2))
        router.http.close()  # keep the fleet; retire only the router
        router = None
        fleet.spawn(1)  # refill the killed slot (no auto-respawn)
        fleet.wait_ready(n_fleet, timeout=600)

        # ---- phase ON: affinity + shipping (fresh system prompt, so
        # nothing phase OFF cached can leak into the measurement)
        router = serve_fleet(fleet, fleet_kv="on")
        on_reduction, _ = calm_phase(router, prompts_for(3))
        on_lats, on_errs, resumes = hammer_phase(
            router, prompts_for(3), wait_ships=True)

        time.sleep(1.0)  # let heartbeat probes fold final ship stats
        stats = fleet.snapshot()["prefix_cache"]
        with urllib.request.urlopen(f"{router.url}/metrics",
                                    timeout=30) as r:
            metrics_text = r.read().decode()
        scraped = all(
            s in metrics_text
            for s in ("dl4j_fleet_prefix_affinity_hits",
                      "dl4j_fleet_prefix_page_ships"))

        op99, fp99 = p99(on_lats), p99(off_lats)
        # "zero affinity-induced regression": the same hammer+kill
        # without affinity is the control; allow measurement noise
        p99_ok = bool(op99 and fp99 and op99 <= max(1.5 * fp99,
                                                    fp99 + 1.0))
        return {
            "value": round(on_reduction, 2),
            "unit": "fleet_prefill_token_reduction",
            "replicas": n_fleet,
            "calm_requests": n_calm,
            "hammer_streams": n_hammer,
            "reduction_affinity_off": round(off_reduction, 2),
            "reduction_affinity_on": round(on_reduction, 2),
            "affinity_hits": stats["affinity"]["hits"],
            "affinity_hit_rate": stats["affinity"]["rate"],
            "page_ships": stats["page_ships"],
            "ship_bytes": stats["ship_bytes"],
            "ship_failures": stats["ship_failures"],
            "stream_failures": len(on_errs) + len(off_errs),
            "failure_sample": (on_errs + off_errs)[:3],
            "failover_resumes": resumes,
            "p99_off_ms": round(fp99 * 1e3, 1) if fp99 else None,
            "p99_on_ms": round(op99 * 1e3, 1) if op99 else None,
            "gate_reduction_4x": on_reduction >= 4.0,
            "gate_beats_affinity_off": on_reduction > off_reduction,
            "gate_zero_stream_failures": not (on_errs or off_errs),
            "gate_no_affinity_p99_regression": p99_ok,
            "gate_affinity_hits": stats["affinity"]["hits"] >= 1,
            "gate_page_shipped": stats["page_ships"] >= 1,
            "gate_metrics_scraped": scraped,
        }
    finally:
        if router is not None:
            router.close(stop_replicas=True)
        else:
            fleet.close(stop_replicas=True)
        shutil.rmtree(work, ignore_errors=True)


def bench_disagg():
    """Disaggregated-roles drill (docs/FLEET.md "Disaggregated
    roles"): a long-prompt storm against a prefill=1/decode=2 fleet,
    with a second model pooled on the same registry. Four legs over
    real replica processes:

    - calm: sequential long-prompt streams on the disagg fleet set
      the decode inter-token p99 baseline.
    - storm: staggered concurrent long-prompt streams — every prompt
      hands off (router /prefill -> kv_donor -> page ship), so the
      decode replicas prefill only tails and inter-token pacing holds
      near calm; concurrent second-model traffic proves per-model
      routing isolation (the m2 replica's prefill-token ledger must
      match EXACTLY the m2 prompts submitted).
    - kill: the same storm with the prefill replica SIGKILLed mid-
      flight — every handoff that dies falls back to plain unified
      prefill with zero client-visible failures.
    - control: the same storm on a unified fleet of equal decode
      capacity, where storm prefills run inline on the decode
      scheduler and inflate inter-token gaps.

    Gates: storm decode p99 <= 1.5x calm, >= 1 handoff per storm
    prompt, zero cross-model routing errors, zero handoff-induced
    stream failures (including the SIGKILL leg), and the
    `dl4j_disagg_*` counters scraped live off the router's /metrics."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving.fleet import Fleet, ReplicaSpawner
    from deeplearning4j_tpu.serving.router import serve_fleet
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    fast = _fast()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_disagg_")
    ckpt = os.path.join(work, "disagg.ckpt")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    spec = os.path.join(work, "tf.json")
    with open(spec, "w") as f:
        _json.dump({"vocab_size": 17, "d_model": 32, "n_heads": 2,
                    "n_layers": 2, "d_ff": 64, "max_len": 96,
                    "interpret": fast, "seed": 0}, f)
    # pace token emission so inter-token gaps are measurable and the
    # SIGKILL lands while handoffs/streams are genuinely in flight;
    # the gap a storm ADDS on top of this pace is the signal
    delay_s = 0.1
    env = dict(os.environ,
               **chaos_mod.env_spec([chaos_mod.Rule(
                   "generate.midstream", "delay", delay_s=delay_s)]))

    def spawner(role=None, model_id=None):
        args = ["--max-delay-ms", "1", "--transformer", spec,
                "--slots", "8", "--page-size", "8",
                "--kv-pages", "64", "--fleet-kv", "on",
                "--kv-ship-timeout", "10"]
        if role is not None:
            args += ["--role", role]
        if model_id is not None:
            args += ["--model-id", model_id]
        return ReplicaSpawner(ckpt, serve_args=args, env=env)

    # the storm's weapon is prompt-length VARIETY: page_size=8 /
    # max_len=96 gives the prefill bucket ladder (8,16,32,64,96);
    # calm traffic lives in bucket 64 (length 42), the storm cycles
    # lengths that hit the three buckets calm never touched — on a
    # unified fleet each novel bucket compiles INLINE on the decode
    # scheduler and craters inter-token pacing, on the disagg fleet
    # those compiles land on the prefill replica while the decode
    # replicas prefill only warm-bucket tails
    calm_len = 42
    storm_lens = (12, 20, 70)       # buckets 16, 32, 96
    n_tokens = 10
    n_calm = 4 if fast else 8
    n_storm = 6 if fast else 9
    n_m2 = 3

    def prompts_for(seed, n, length):
        rng = np.random.RandomState(seed)
        if isinstance(length, tuple):
            lens = [length[i % len(length)] for i in range(n)]
        else:
            lens = [length] * n
        return [rng.randint(1, 17, (ln,)).tolist() for ln in lens]

    def post(url, payload, headers=(), timeout=300):
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(dict(headers))
        req = urllib.request.Request(
            url, data=_json.dumps(payload).encode(), headers=hdrs)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return _json.loads(r.read())

    def stream_gaps(router, prompt, model_id=None):
        """One streamed request; returns (inter-token gaps s, ok)."""
        body = {"prompt": [prompt], "max_tokens": n_tokens,
                "stream": True}
        hdrs = {"Content-Type": "application/json"}
        if model_id is not None:
            hdrs["X-Model"] = model_id
        req = urllib.request.Request(
            f"{router.url}/generate", data=_json.dumps(body).encode(),
            headers=hdrs)
        stamps, events = [], []
        with urllib.request.urlopen(req, timeout=300) as r:
            for ln in r:
                if ln.strip():
                    events.append(_json.loads(ln))
                    if "token" in events[-1]:
                        stamps.append(time.perf_counter())
        ok = (events and events[-1].get("done")
              and len(stamps) == n_tokens)
        return ([b - a for a, b in zip(stamps, stamps[1:])], ok)

    def storm(router, prompts, stagger_s=0.06, kill=None,
              model_id=None):
        """Staggered concurrent streams; later prompts' prefills land
        while earlier streams decode — on a unified fleet that
        co-schedules them with decode, on the disagg fleet they run on
        the prefill replica. Returns (gaps, errors)."""
        gaps, errors = [], []
        lock = threading.Lock()

        def worker(i):
            try:
                g, ok = stream_gaps(router, prompts[i],
                                    model_id=model_id)
                with lock:
                    gaps.extend(g)
                    if not ok:
                        errors.append(f"stream {i}: bad terminal")
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"stream {i}: {e!r}")

        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True)
                   for i in range(len(prompts))]
        for i, t in enumerate(threads):
            t.start()
            time.sleep(stagger_s)
            if kill is not None and i == len(threads) // 2:
                kill()
        for t in threads:
            t.join(timeout=300)
        return gaps, errors

    def p99(xs):
        return (sorted(xs)[max(0, int(len(xs) * 0.99) - 1)]
                if xs else None)

    def disagg_counters(router):
        with urllib.request.urlopen(f"{router.url}/stats",
                                    timeout=30) as r:
            return _json.loads(r.read())["fleet"]["disagg"]

    # ---- disagg fleet: prefill=1/decode=2 for m1, unified=1 for m2
    fleet = Fleet(heartbeat_interval=0.2, heartbeat_timeout=3.0,
                  breaker_threshold=2, breaker_reset_s=0.4)
    router = None
    try:
        fleet.add_pool(model_id="m1", role="prefill",
                       spawner=spawner("prefill", "m1"))
        fleet.add_pool(model_id="m1", role="decode",
                       spawner=spawner("decode", "m1"))
        fleet.add_pool(model_id="m2", role="unified",
                       spawner=spawner(None, "m2"))
        pre_rep = fleet.spawn_pool("m1", "prefill", 1)[0]
        fleet.spawn_pool("m1", "decode", 2)
        m2_rep = fleet.spawn_pool("m2", "unified", 1)[0]
        fleet.wait_ready(4, timeout=600)
        router = serve_fleet(fleet, fleet_kv="on")

        # warmup streams compile the calm buckets + decode step on
        # every decode replica so the calm baseline measures pacing,
        # not one-time compiles (sequential spread covers the pool)
        for pr in prompts_for(11, 4, calm_len):
            stream_gaps(router, pr, model_id="m1")
        calm_gaps = []
        for pr in prompts_for(1, n_calm, calm_len):
            g, ok = stream_gaps(router, pr, model_id="m1")
            assert ok, "calm stream lost tokens"
            calm_gaps.extend(g)

        # ---- storm + concurrent second-model traffic
        before = disagg_counters(router)
        m2_prompts = prompts_for(7, n_m2, 24)
        m2_errors = []

        def m2_traffic():
            for pr in m2_prompts:
                try:
                    out = post(f"{router.url}/generate",
                               {"prompt": [pr], "max_tokens": 2,
                                "model_id": "m2"})
                    if out.get("finish_reasons") != ["max_tokens"]:
                        m2_errors.append("bad finish")
                except Exception as e:  # noqa: BLE001
                    m2_errors.append(repr(e))

        m2_thread = threading.Thread(target=m2_traffic, daemon=True)
        m2_thread.start()
        storm_prompts = prompts_for(2, n_storm, storm_lens)
        storm_gaps, storm_errors = storm(router, storm_prompts,
                                         model_id="m1")
        m2_thread.join(timeout=300)
        after = disagg_counters(router)
        handoffs_storm = after["handoffs"] - before["handoffs"]

        # per-model isolation ledger: the m2 replica prefilled EXACTLY
        # the m2 prompts — one leaked request either way breaks it
        m2_expected = sum(len(p) for p in m2_prompts)
        m2_stats = m2_rep.client.stats()
        m2_prefill = m2_stats["generate"]["decode"]["prefill_tokens"]

        # ---- kill leg: SIGKILL the prefill replica mid-storm
        kill_prompts = prompts_for(3, n_storm, storm_lens)
        _, kill_errors = storm(
            router, kill_prompts, model_id="m1",
            kill=lambda: chaos_mod.sigkill(pre_rep.proc))
        final = disagg_counters(router)

        with urllib.request.urlopen(f"{router.url}/metrics",
                                    timeout=30) as r:
            metrics_text = r.read().decode()
        scraped = all(s in metrics_text for s in
                      ("dl4j_disagg_handoffs",
                       "dl4j_disagg_handoff_bytes",
                       "dl4j_disagg_handoff_failures",
                       "dl4j_disagg_fallbacks",
                       "dl4j_fleet_role_replicas"))
        router.close(stop_replicas=True)
        router = None

        # ---- control: unified fleet of equal decode capacity
        ctl = Fleet(spawner=spawner(), heartbeat_interval=0.2,
                    heartbeat_timeout=3.0, breaker_threshold=2,
                    breaker_reset_s=0.4)
        ctl_router = None
        try:
            ctl.spawn(3)
            ctl.wait_ready(3, timeout=600)
            ctl_router = serve_fleet(ctl, fleet_kv="on")
            for pr in prompts_for(12, 6, calm_len):   # warm the pool
                stream_gaps(ctl_router, pr)
            ctl_calm_gaps = []
            for pr in prompts_for(5, n_calm, calm_len):
                g, _ = stream_gaps(ctl_router, pr)
                ctl_calm_gaps.extend(g)
            ctl_gaps, ctl_errors = storm(
                ctl_router, prompts_for(4, n_storm, storm_lens))
        finally:
            if ctl_router is not None:
                ctl_router.close(stop_replicas=True)
            else:
                ctl.close(stop_replicas=True)

        cp99, sp99 = p99(calm_gaps), p99(storm_gaps)
        ucp99, up99 = p99(ctl_calm_gaps), p99(ctl_gaps)
        sp99_ms = round(sp99 * 1e3, 1) if sp99 else None
        return {
            "value": sp99_ms,
            "unit": "decode_inter_token_p99_ms_under_prefill_storm",
            "replicas": {"m1": {"prefill": 1, "decode": 2},
                         "m2": {"unified": 1}, "control_unified": 3},
            "calm_streams": n_calm,
            "storm_streams": n_storm,
            "calm_p99_ms": round(cp99 * 1e3, 1) if cp99 else None,
            "storm_p99_ms": sp99_ms,
            "unified_calm_p99_ms":
                round(ucp99 * 1e3, 1) if ucp99 else None,
            "unified_storm_p99_ms":
                round(up99 * 1e3, 1) if up99 else None,
            "handoffs_storm": handoffs_storm,
            "handoff_bytes": final["handoff_bytes"],
            "handoff_failures": final["handoff_failures"],
            "fallbacks": final["fallbacks"],
            "m2_requests": n_m2,
            "m2_prefill_tokens": m2_prefill,
            "m2_prefill_expected": m2_expected,
            "stream_failures":
                len(storm_errors) + len(kill_errors) + len(m2_errors),
            "failure_sample":
                (storm_errors + kill_errors + m2_errors)[:3],
            "gate_decode_p99_bounded":
                bool(cp99 and sp99 and sp99 <= 1.5 * cp99),
            "gate_handoff_per_storm_prompt":
                handoffs_storm >= n_storm,
            "gate_zero_cross_model_errors":
                not m2_errors and m2_prefill == m2_expected,
            "gate_zero_handoff_failures":
                not (storm_errors or kill_errors),
            "gate_unified_control_degrades":
                bool(ucp99 and up99 and up99 > 1.5 * ucp99),
            "gate_metrics_scraped": scraped,
        }
    finally:
        if router is not None:
            router.close(stop_replicas=True)
        else:
            fleet.close(stop_replicas=True)
        shutil.rmtree(work, ignore_errors=True)


def bench_slo_tiers():
    """SLO tiers drill (docs/SERVING.md "Priority tiers"): saturate a
    fleet's decode slots with batch-tier /generate streams, then run
    interactive requests through the flood. Interactive latency must
    hold (preemption evicts batch slots past the fair share), and the
    preempted batch work must be LOSSLESS: the router's durable-stream
    resume re-admits each preempted row, so every batch stream still
    delivers its full token budget gapless, duplicate-free, and
    fleet's decode slots with batch-tier /generate streams, then run
    interactive requests through the flood. Interactive latency must
    hold (preemption evicts batch slots past the fair share), and the
    preempted batch work must be LOSSLESS: the router's durable-stream
    resume re-admits each preempted row, so every batch stream still
    delivers its full token budget gapless, duplicate-free, and
    bit-identical to a calm reference. Gates: bounded interactive p99
    vs the calm baseline, zero lost/duplicated batch rows, at least
    one observed preemption, and the three-way page-pool invariant
    intact at the end."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving.fleet import Fleet, ReplicaSpawner
    from deeplearning4j_tpu.serving.router import serve_fleet
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    fast = _fast()
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(4).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=3)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_slo_")
    ckpt = os.path.join(work, "slo.ckpt")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    spec = os.path.join(work, "tf.json")
    with open(spec, "w") as f:
        _json.dump({"vocab_size": 17, "d_model": 32, "n_heads": 2,
                    "n_layers": 2, "d_ff": 64, "max_len": 96,
                    "interpret": fast,
                    "seed": 0}, f)
    # pace the decode scheduler itself so interactive arrivals land
    # while batch streams HOLD slots: with the compile cache hot a
    # replica decodes ~2 ms/token, and an unpaced flood frees every
    # slot before a probe can arrive — decode.step is the chaos point
    # at the top of every scheduler pass
    delay_s = 0.01 if fast else 0.02
    step_s = 0.03 if fast else 0.05
    env = dict(os.environ,
               **chaos_mod.env_spec([
                   chaos_mod.Rule("generate.midstream", "delay",
                                  delay_s=delay_s),
                   chaos_mod.Rule("decode.step", "delay",
                                  delay_s=step_s)]))
    # 4 slots, batch_share 0.5: an idle fleet lets batch take all 4,
    # and the first interactive arrival preempts down toward 2
    spawner = ReplicaSpawner(
        ckpt, serve_args=["--max-delay-ms", "1", "--transformer", spec,
                          "--slots", "4", "--page-size", "8",
                          "--batch-share", "0.5"],
        env=env)

    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    batch_tokens = 48 if fast else 64
    inter_tokens = 4
    n_batch_streams = 4
    n_probes = 12 if fast else 24

    def p99(xs):
        return (sorted(xs)[max(0, int(len(xs) * 0.99) - 1)]
                if xs else None)

    def interactive_once():
        body = _json.dumps({"prompt": [prompt],
                            "max_tokens": inter_tokens}).encode()
        req = urllib.request.Request(
            f"{router.url}/generate", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=300) as r:
            reply = _json.loads(r.read())
        assert "tokens" in reply, reply
        return time.perf_counter() - t0, reply["tokens"][0]

    def batch_stream(events):
        body = _json.dumps({"prompt": [prompt],
                            "max_tokens": batch_tokens,
                            "priority": "batch",
                            "stream": True}).encode()
        req = urllib.request.Request(
            f"{router.url}/generate", data=body,
            headers={"Content-Type": "application/json",
                     "X-Priority": "batch"})
        with urllib.request.urlopen(req, timeout=300) as r:
            for ln in r:
                if ln.strip():
                    events.append(_json.loads(ln))

    fleet = Fleet(spawner=spawner, heartbeat_interval=0.2,
                  heartbeat_timeout=3.0, shed_high_water=64)
    router = None
    try:
        fleet.spawn(1)
        fleet.wait_ready(1, timeout=300)
        router = serve_fleet(fleet)

        # calm baseline: compile the decode path, take the reference
        # continuation (deterministic weights: tier never changes the
        # tokens), then measure undisturbed interactive latency
        _, ref_inter = interactive_once()
        ref_events = []
        batch_stream(ref_events)
        ref_batch = [e["token"] for e in ref_events if "token" in e]
        assert len(ref_batch) == batch_tokens
        calm = [interactive_once()[0] for _ in range(n_probes)]
        calm_p99 = p99(calm)

        # flood: saturate every slot with batch streams, then push the
        # interactive probes through the flood
        all_events = [[] for _ in range(n_batch_streams)]
        errors = []

        def worker(i):
            try:
                batch_stream(all_events[i])
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True)
                   for i in range(n_batch_streams)]
        for t in threads:
            t.start()
        # wait until the flood actually OCCUPIES every decode slot
        # (router-side outstanding also counts relay-lagged streams)
        rep0 = next(iter(fleet._replicas.values()))
        occupy_by = time.monotonic() + 30.0
        while time.monotonic() < occupy_by:
            occ = rep0.client.stats()["generate"]["decode"][
                "tiers"]["occupied"]
            if occ["batch"] >= n_batch_streams:
                break
            time.sleep(0.02)
        flood = []
        util_peak = 0.0
        for i in range(n_probes):
            dt, toks = interactive_once()
            flood.append(dt)
            assert toks == ref_inter, "interactive tokens diverged"
            util_peak = max(util_peak,
                            fleet.snapshot()["tiers"]["utilization"])
        flood_p99 = p99(flood)
        for t in threads:
            t.join(timeout=300)

        # lossless batch lane: every stream full-length, gapless,
        # duplicate-free, bit-identical to the calm reference
        failures = list(errors)
        resumes = 0
        for ev in all_events:
            toks = [e for e in ev if "token" in e]
            if [e["token_index"] for e in toks] != list(
                    range(batch_tokens)):
                failures.append("batch token_index gap/dup")
            if [e["token"] for e in toks] != ref_batch:
                failures.append("batch tokens diverged from reference")
            if not (ev and ev[-1].get("done")):
                failures.append("batch stream ended without done")
            else:
                resumes += ev[-1].get("preempt_resumes", 0)

        snap = fleet.snapshot()
        rep = next(iter(fleet._replicas.values()))
        sdec = rep.client.stats()["generate"]["decode"]
        preemptions = sdec["tiers"]["preemptions"]
        pages_leaked = sdec["pages_in_use"]  # all streams done by now
        bound = max(1.5 * calm_p99, 2.0) if calm_p99 else 2.0
        return {
            "value": round(flood_p99 * 1e3, 2) if flood_p99 else None,
            "unit": "interactive_p99_under_flood_ms",
            "lower_is_better": True,
            "batch_streams": n_batch_streams,
            "batch_tokens_per_stream": batch_tokens,
            "interactive_probes": n_probes,
            "calm_p99_ms": (round(calm_p99 * 1e3, 2)
                            if calm_p99 else None),
            "flood_p99_ms": (round(flood_p99 * 1e3, 2)
                             if flood_p99 else None),
            "p99_bound_ms": round(bound * 1e3, 2),
            "preemptions": preemptions,
            "preempt_resumes": snap["tiers"]["preempt_resumes"],
            "client_preempt_resumes": resumes,
            "batch_row_failures": len(failures),
            "failure_sample": failures[:3],
            "utilization_peak": round(util_peak, 4),
            "tier_requests": snap["tiers"]["requests"],
            "gate_interactive_p99_bounded": bool(
                flood_p99 and flood_p99 <= bound),
            "gate_zero_batch_loss": not failures,
            "gate_preempted": preemptions >= 1,
            "gate_lossless_resume":
                snap["tiers"]["preempt_resumes"] >= 1,
            "gate_no_leaked_pages": pages_leaked == 0,
            "gate_one_decode_program":
                sdec["decode_step_programs"] == 1,
        }
    finally:
        if router is not None:
            router.close(stop_replicas=True)
        else:
            fleet.close(stop_replicas=True)
        shutil.rmtree(work, ignore_errors=True)


def bench_train_elastic():
    """Self-healing elastic training drills (ISSUE 9,
    docs/FAULT_TOLERANCE.md "Supervisor runbook"). Three drills over a
    TrainingSupervisor with 2 out-of-process workers:

    (a) **kill drill** — SIGKILL one worker mid-run; the supervisor
        evicts (process exit is observed directly), respawns, the wave
        re-forms, and the completed run's params must be BIT-IDENTICAL
        to an uninterrupted run at the same wave schedule (canonical
        job-seq fold order + exact wave membership). Recovery time
        (kill -> replacement RUNNING) is the primary metric.
    (b) **capacity-loss drill** — SIGKILL with respawn budget 0; the
        supervisor flushes and restarts the wave from the last
        COMMITTED sharded checkpoint resharded 2 -> 1 workers, with
        ZERO lost or double-trained examples (the folded batch-index
        trace must tile the stream exactly once).
    (c) **SIGSTOP drill** — a stopped worker still holds TCP, so
        liveness never lapses (heartbeat_timeout is set far beyond the
        run); only the steps-per-heartbeat progress watermark may evict
        it, within its configured window.
    """
    import tempfile
    import threading

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.iris import load_iris
    from deeplearning4j_tpu.scaleout.api import CollectionJobIterator
    from deeplearning4j_tpu.scaleout.registry import ConfigRegistry
    from deeplearning4j_tpu.scaleout.supervisor import (TrainingSupervisor,
                                                        WorkerSpawner)
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    conf_json = (NeuralNetConfiguration.builder()
                 .lr(0.1).n_in(4).activation_function("tanh")
                 .optimization_algo("iteration_gradient_descent")
                 .num_iterations(2).use_adagrad(False).momentum(0.0)
                 .list(2).hidden_layer_sizes([8])
                 .override(1, layer="output", loss_function="mcxent",
                           activation_function="softmax", n_out=3)
                 .pretrain(False).build().to_json())
    x, y = load_iris()
    x, y = np.asarray(x), np.asarray(y)
    rng = np.random.RandomState(0)
    batches = [(x[i], y[i])
               for i in (rng.choice(len(x), 24, replace=False)
                         for _ in range(6))]
    work = tempfile.mkdtemp(prefix="dl4j_bench_elastic_")

    def supervisor(tag, **kw):
        registry_root = os.path.join(work, f"reg_{tag}")
        jobs = [DataSet(bx, by) for bx, by in batches]
        kw.setdefault("heartbeat_timeout", 2.0)
        kw.setdefault("progress_timeout", 90.0)
        return TrainingSupervisor(
            CollectionJobIterator(jobs), run_name=tag,
            registry=ConfigRegistry(registry_root),
            performer_class=("deeplearning4j_tpu.scaleout.perform."
                            "NeuralNetWorkPerformer"),
            performer_conf={"conf_json": conf_json, "epochs": 1},
            n_workers=2, conf_json=conf_json,
            spawner=WorkerSpawner(registry_root, tag), **kw)

    n_jobs = len(batches)
    exact = list(range(n_jobs))

    # -------- uninterrupted reference (same wave schedule)
    ref = supervisor("ref").run(timeout=240.0)

    # -------- (a) kill drill: SIGKILL -> respawn -> bit-identical
    sup_a = supervisor("kill", checkpoint_dir=os.path.join(work, "ck_a"),
                       max_respawns=2, respawn_backoff_s=0.05)
    drill_a = {}

    def killer():
        deadline = time.time() + 120
        while time.time() < deadline:
            for rec in list(sup_a.members.values()):
                if (rec.performed >= 1 and rec.proc is not None
                        and rec.generation == 0):
                    chaos_mod.sigkill(rec.proc)
                    t_kill = time.monotonic()
                    drill_a["killed"] = rec.id
                    while time.monotonic() - t_kill < 120:
                        if any(r.generation > 0 and r.state == "running"
                               for r in list(sup_a.members.values())):
                            drill_a["recovery_s"] = round(
                                time.monotonic() - t_kill, 3)
                            return
                        time.sleep(0.005)
                    return
            time.sleep(0.005)

    kt = threading.Thread(target=killer, daemon=True)
    kt.start()
    final_a = sup_a.run(timeout=240.0)
    kt.join(timeout=10)
    bit_identical = bool(final_a is not None
                         and np.array_equal(ref, final_a))
    trace_a_exact = sorted(sup_a.folded_seqs) == exact

    # -------- (b) capacity loss: no respawn budget -> resharded resume
    sup_b = supervisor("caploss",
                       checkpoint_dir=os.path.join(work, "ck_b"),
                       max_respawns=0)
    drill_b = {}

    def killer_b():
        deadline = time.time() + 120
        while time.time() < deadline:
            if sup_b.waves >= 1:
                for rec in list(sup_b.members.values()):
                    if rec.performed >= 1 and rec.proc is not None:
                        chaos_mod.sigkill(rec.proc)
                        drill_b["killed"] = rec.id
                        return
            time.sleep(0.005)

    kbt = threading.Thread(target=killer_b, daemon=True)
    kbt.start()
    final_b = sup_b.run(timeout=240.0)
    kbt.join(timeout=10)
    resume = (sup_b.resume_events[-1] if sup_b.resume_events else {})
    trace_b_exact = sorted(sup_b.folded_seqs) == exact
    resharded = bool(resume.get("resharded")
                     and resume.get("survivors") == 1)

    # -------- (c) SIGSTOP: watermark detection within its window
    progress_timeout = 2.0
    sup_c = supervisor("sigstop", max_respawns=1,
                       respawn_backoff_s=0.05,
                       heartbeat_timeout=600.0,  # liveness CANNOT evict
                       progress_timeout=progress_timeout)
    drill_c = {}

    def stopper():
        deadline = time.time() + 120
        while time.time() < deadline:
            for rec in list(sup_c.members.values()):
                if (rec.performed >= 1 and rec.proc is not None
                        and rec.generation == 0):
                    chaos_mod.sigstop(rec.proc)
                    drill_c["stopped"] = rec.id
                    drill_c["t"] = time.monotonic()
                    return
            time.sleep(0.005)

    st = threading.Thread(target=stopper, daemon=True)
    st.start()
    final_c = sup_c.run(timeout=240.0)
    st.join(timeout=10)
    detect_s = None
    if drill_c.get("stopped"):
        rec = sup_c.members[drill_c["stopped"]]
        if rec.evicted_at is not None:
            detect_s = round(rec.evicted_at - drill_c["t"], 3)
        drill_c["reason"] = rec.eviction_reason
    # detection bound: the job must first be dispatched to the stopped
    # member (one wave) and then sit a full watermark window; allow one
    # extra window of monitor slack
    detect_bound = 3 * progress_timeout + 5.0
    sigstop_ok = bool(
        detect_s is not None and detect_s <= detect_bound
        and (drill_c.get("reason") or "").startswith("hung")
        and final_c is not None
        and sorted(sup_c.folded_seqs) == exact)

    return {
        "value": drill_a.get("recovery_s"),
        "unit": "s_kill_to_respawned_running",
        "lower_is_better": True,
        "workers": 2, "jobs": n_jobs,
        "kill_drill": {**drill_a, "bit_identical": bit_identical,
                       "trace_exact": trace_a_exact,
                       "respawns": sup_a.respawns_used},
        "capacity_loss_drill": {**drill_b, "resume": resume,
                                "trace_exact": trace_b_exact},
        "sigstop_drill": {**drill_c, "detect_s": detect_s,
                          "bound_s": detect_bound},
        "gate_bit_identical_after_respawn": bit_identical,
        "gate_no_lost_or_double_trained": bool(trace_a_exact
                                               and trace_b_exact),
        "gate_resharded_resume": resharded,
        "gate_recovery_bounded": bool(
            drill_a.get("recovery_s") is not None
            and drill_a["recovery_s"] <= 60.0
            and resume.get("recovery_s") is not None
            and resume["recovery_s"] <= 60.0),
        "gate_sigstop_watermark": sigstop_ok,
    }


def bench_controlplane():
    """Control-plane crash-safety drills (ISSUE 10,
    docs/FAULT_TOLERANCE.md "Who watches the watcher" + docs/FLEET.md
    "Router restart runbook"). Two REAL-PROCESS drills over the
    journaled (`--state-dir`) control plane:

    (a) **supervisor-kill drill** — `cli watchdog -- train --elastic 2
        --state-dir ...`; SIGKILL the supervisor process as soon as a
        COMMITTED checkpoint proves the run is mid-flight. The
        watchdog's next incarnation must RE-ADOPT the surviving worker
        processes (adopted >= 1, zero respawns of live pids) and
        complete the run with params BIT-IDENTICAL to an uninterrupted
        reference and `folded == jobs` (zero lost / double-trained
        examples).
    (b) **router-kill drill** — `cli fleet --replicas 2 --state-dir`
        under a /predict hammer; SIGKILL the router process
        mid-hammer, restart it immediately (the bench plays watchdog).
        The restarted incarnation must readmit every journaled replica
        WARM through /readyz: same pids (zero respawns), per-replica
        compiled-program counts unchanged (zero recompiles), client
        errors confined to the kill->readmission window, and recovery
        (restart launch -> first routed success) under 5 s on the CPU
        smoke.
    """
    import signal
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.checkpoint.format import list_steps
    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.iris import load_iris
    from deeplearning4j_tpu.scaleout.checkpoint import load_checkpoint
    from deeplearning4j_tpu.testing import chaos as chaos_mod

    work = tempfile.mkdtemp(prefix="dl4j_bench_cp_")
    x, y = load_iris()
    data = np.hstack([np.asarray(x),
                      np.argmax(np.asarray(y), axis=1)[:, None]])
    csv = os.path.join(work, "iris.csv")
    np.savetxt(csv, data, delimiter=",", fmt="%.6f")
    conf_json = (NeuralNetConfiguration.builder()
                 .lr(0.1).n_in(4).activation_function("tanh")
                 .optimization_algo("iteration_gradient_descent")
                 .num_iterations(2).use_adagrad(False).momentum(0.0)
                 .list(2).hidden_layer_sizes([8])
                 .override(1, layer="output", loss_function="mcxent",
                           activation_function="softmax", n_out=3)
                 .pretrain(False).build().to_json())
    conf_path = os.path.join(work, "conf.json")
    with open(conf_path, "w") as f:
        f.write(conf_json)
    import sys as _sys

    py = _sys.executable

    def train_args(out):
        # --straggler-factor 50: compile jitter must not evict anyone
        # mid-drill (this drill is about the control plane, not the
        # straggler defense)
        return ["train", "--elastic", "2", "-i", csv, "-m", conf_path,
                "-o", out, "--batch-size", "8", "--epochs", "6",
                "--straggler-factor", "50", "--run-timeout", "240"]

    # ---- (a) supervisor-kill drill --------------------------------
    ref_out = os.path.join(work, "ref.ckpt")
    ref = subprocess.run(
        [py, "-m", "deeplearning4j_tpu.cli"] + train_args(ref_out)
        + ["--checkpoint-dir", os.path.join(work, "ck_ref")],
        capture_output=True, text=True, timeout=300, cwd=HERE)
    if ref.returncode != 0:
        raise RuntimeError(f"reference elastic run failed: "
                           f"{ref.stdout[-500:]} {ref.stderr[-500:]}")

    state = os.path.join(work, "state")
    ck = os.path.join(work, "ck")
    drill_out = os.path.join(work, "drill.ckpt")
    cmd = ([py, "-m", "deeplearning4j_tpu.cli", "watchdog",
            "--max-restarts", "3", "--backoff", "0.2", "--"]
           + train_args(drill_out)
           + ["--state-dir", state, "--checkpoint-dir", ck])
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            cwd=HERE)
    children, killed, restart_ts = [], [], []
    drill_sup = {}

    def killer():
        deadline = time.time() + 240
        while time.time() < deadline and not killed:
            if children:
                try:
                    if list_steps(ck):
                        chaos_mod.sigkill(children[0])
                        killed.append(time.monotonic())
                        return
                except (OSError, ProcessLookupError):
                    return
            time.sleep(0.05)

    threading.Thread(target=killer, daemon=True).start()
    lines = []
    for line in proc.stdout:
        lines.append(line)
        if line.startswith("{"):
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if "watchdog_child" in e:
                children.append(e["watchdog_child"])
                restart_ts.append(time.monotonic())
            elif "saved" in e:
                drill_sup = e
    rc = proc.wait(timeout=60)
    sup_restart_s = (round(restart_ts[1] - killed[0], 3)
                     if killed and len(restart_ts) > 1 else None)
    ref_net, _ = load_checkpoint(ref_out)
    sup_bit_identical = False
    if rc == 0 and os.path.exists(drill_out):
        drill_net, _ = load_checkpoint(drill_out)
        sup_bit_identical = bool(np.array_equal(
            np.asarray(ref_net.params()),
            np.asarray(drill_net.params())))
    sup_exact = bool(drill_sup
                     and drill_sup.get("folded") == drill_sup.get("jobs"))
    sup_adopted = bool(drill_sup and drill_sup.get("adopted", 0) >= 1
                       and drill_sup.get("respawns", 1) == 0)

    # ---- (b) router-kill drill ------------------------------------
    fstate = os.path.join(work, "fstate")
    fleet_cmd = [py, "-m", "deeplearning4j_tpu.cli", "fleet",
                 "-m", conf_path, "--replicas", "2",
                 "--state-dir", fstate,
                 "--heartbeat-interval", "0.2",
                 "--request-timeout", "10"]

    def launch_router():
        p = subprocess.Popen(fleet_cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.DEVNULL, text=True,
                             start_new_session=True, cwd=HERE)
        announce = None
        for line in p.stdout:
            if line.startswith("{") and '"router"' in line:
                announce = json.loads(line)
                break
        if announce is None:
            p.kill()
            raise RuntimeError("router never announced")
        # keep draining so the child never blocks on a full pipe
        threading.Thread(target=lambda: [None for _ in p.stdout],
                         daemon=True).start()
        return p, announce

    def get_json(url, timeout=10.0):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())

    def replica_programs(endpoints):
        """Per-replica compiled-program counts, scraped from each
        replica's OWN /stats — unchanged across the router restart
        means the warm engines never recompiled."""
        out = {}
        for url in endpoints:
            stats = get_json(url + "/stats")
            out[url] = stats.get("replicas", {}).get(
                "compiled_programs")
        return out

    results = []          # (t, ok) per hammer request
    hammer_stop = threading.Event()
    router_url = {}

    def hammer():
        body = json.dumps({"inputs": data[:4, :4].tolist()}).encode()
        while not hammer_stop.is_set():
            url = router_url.get("url")
            if url is None:
                time.sleep(0.02)
                continue
            t = time.monotonic()
            try:
                req = urllib.request.Request(
                    url + "/predict", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as r:
                    ok = r.status == 200
            except Exception:
                ok = False
            results.append((t, ok))
            time.sleep(0.01)

    p1 = p2 = None
    replica_pids = []
    try:
        p1, ann1 = launch_router()
        endpoints = ann1["endpoints"]
        # both replicas ready before the drill starts
        deadline = time.time() + 180
        while time.time() < deadline:
            if get_json(ann1["router"] + "/readyz",
                        timeout=5).get("ready_replicas", 0) >= 2:
                break
            time.sleep(0.1)
        snap = get_json(ann1["router"] + "/stats")["fleet"]
        replica_pids = sorted(r["pid"]
                              for r in snap["replicas"].values()
                              if "pid" in r)
        programs_before = replica_programs(endpoints)
        router_url["url"] = ann1["router"]
        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.5)  # steady traffic through the warm fleet
        t_kill = time.monotonic()
        chaos_mod.sigkill(p1.pid)  # the router process, not the group:
        # replicas live in their own sessions and must survive
        t_launch = time.monotonic()
        p2, ann2 = launch_router()
        router_url["url"] = ann2["router"]
        t_announce = time.monotonic()
        # first routed success after the restart
        t_ok = None
        deadline = time.time() + 60
        while time.time() < deadline and t_ok is None:
            t_ok = next((t for t, ok in list(results)
                         if ok and t > t_announce), None)
            time.sleep(0.02)
        time.sleep(1.0)  # post-recovery traffic for the window audit
        hammer_stop.set()
        for t in threads:
            t.join(timeout=5)
        snap2 = get_json(ann2["router"] + "/stats")["fleet"]
        replica_pids2 = sorted(r["pid"]
                               for r in snap2["replicas"].values()
                               if "pid" in r)
        programs_after = replica_programs(endpoints)
        failures_after_ok = [t for t, ok in results
                             if not ok and t_ok and t > t_ok]
        recovery_s = (round(t_ok - t_launch, 3)
                      if t_ok is not None else None)
        error_window_s = (round(t_ok - t_kill, 3)
                          if t_ok is not None else None)
        router_drill = {
            "incarnation": ann2.get("incarnation"),
            "adopted": ann2.get("adopted"),
            "replica_pids_before": replica_pids,
            "replica_pids_after": replica_pids2,
            "programs_before": programs_before,
            "programs_after": programs_after,
            "announce_s": round(t_announce - t_launch, 3),
            "recovery_s": recovery_s,
            "error_window_s": error_window_s,
            "requests": len(results),
            "failures": sum(1 for _, ok in results if not ok),
            "failures_after_readmission": len(failures_after_ok),
        }
    finally:
        hammer_stop.set()
        for p in (p1, p2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
        for pid in replica_pids:
            try:
                os.killpg(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass

    gate_router_zero_respawns = bool(
        router_drill["adopted"] == 2
        and replica_pids and router_drill["replica_pids_after"]
        == replica_pids)
    gate_router_zero_recompiles = bool(
        router_drill["programs_before"]
        == router_drill["programs_after"])
    gate_router_recovery = bool(
        router_drill["recovery_s"] is not None
        and router_drill["recovery_s"] <= 5.0)
    gate_error_window = bool(
        router_drill["error_window_s"] is not None
        and router_drill["failures_after_readmission"] == 0
        and router_drill["error_window_s"]
        <= router_drill["announce_s"] + 5.0)

    return {
        "value": router_drill["recovery_s"],
        "unit": "s_router_restart_to_first_routed_success",
        "lower_is_better": True,
        "supervisor_drill": {
            "rc": rc, "summary": drill_sup,
            "restart_s": sup_restart_s,
            "incarnations": len(children),
            "bit_identical": sup_bit_identical,
        },
        "router_drill": router_drill,
        "gate_supervisor_bit_identical": sup_bit_identical,
        "gate_supervisor_zero_lost_or_double": sup_exact,
        "gate_supervisor_adopted_not_respawned": sup_adopted,
        "gate_router_zero_respawns": gate_router_zero_respawns,
        "gate_router_zero_recompiles": gate_router_zero_recompiles,
        "gate_router_recovery_bounded": gate_router_recovery,
        "gate_router_error_window_bounded": gate_error_window,
    }


def bench_pipeline():
    """Train→serve conveyor drill (ISSUE 14, docs/PIPELINE.md): one
    model continuously training AND continuously serving its newest
    good weights, with every process in the chain kill -9'd mid-flight
    under a client request hammer.

    Topology (all real processes): `cli watchdog -- train --elastic 2
    --checkpoint-dir ck` commits sharded steps; `cli fleet --replicas 2`
    serves them behind the router; `cli watchdog -- pipeline` watches
    ck, eval-gates each COMMITTED step on a held-out set, and canary-
    promotes through POST /reload. The drill kills, in order: the
    elastic SUPERVISOR (watchdog restarts it, elastic resume), the
    deployment CONTROLLER (watchdog restarts it, journal resume), one
    REPLICA (fleet evicts it, retries mask the hammer), and the ROUTER
    (the bench relaunches it on the same port; the journal re-adopts
    the surviving replica warm). Then a poisoned checkpoint (random
    weights → eval-fail → quarantine) and an arch-mismatched one
    (canary reload failure → rollback + quarantine) ride the conveyor.

    Gates: zero hammer errors outside the kill→readmission windows; no
    torn promotion — the router's checkpoint-identity /stats shows every
    serving replica on EXACTLY one champion; the fleet converges to the
    newest eval-passed COMMITTED step; both poison steps carry
    QUARANTINED markers; dl4j_pipeline_{promotions,rollbacks,
    quarantines} scraped live from the controller's /metrics. Value:
    seconds from the training run's last commit to the fleet serving
    that step (the conveyor's end-to-end latency).
    """
    import signal
    import socket
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.checkpoint import ShardedModelSaver
    from deeplearning4j_tpu.checkpoint.restore import list_committed_steps
    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.deploy import QUARANTINE_MARKER
    from deeplearning4j_tpu.checkpoint import format as ckfmt
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.testing import chaos as chaos_mod
    import sys as _sys

    py = _sys.executable
    work = tempfile.mkdtemp(prefix="dl4j_bench_pipe_")

    # separable 3-class clusters: the gate spread between a fit net
    # (~1.0 f1) and a random-init poison (~0.33) is wide and reliable
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 3, 240)
    feats = (np.eye(3, 4, dtype=np.float32)[labels] * 4.0
             + 0.3 * rng.randn(240, 4)).astype(np.float32)
    train_csv = os.path.join(work, "train.csv")
    np.savetxt(train_csv, np.hstack([feats[:192], labels[:192, None]]),
               delimiter=",", fmt="%.6f")
    holdout_csv = os.path.join(work, "holdout.csv")
    np.savetxt(holdout_csv, np.hstack([feats[192:],
                                       labels[192:, None]]),
               delimiter=",", fmt="%.6f")

    def build_conf(hidden=8):
        return (NeuralNetConfiguration.builder()
                .lr(0.1).n_in(4).activation_function("tanh")
                .optimization_algo("iteration_gradient_descent")
                .num_iterations(1).use_adagrad(False)
                .list(2).hidden_layer_sizes([hidden])
                .override(1, layer="output", loss_function="mcxent",
                          activation_function="softmax", n_out=3)
                .pretrain(False).build())

    conf_path = os.path.join(work, "conf.json")
    with open(conf_path, "w") as f:
        f.write(build_conf().to_json())
    boot_dir = os.path.join(work, "boot")
    with ShardedModelSaver(boot_dir, sync=True) as s:
        s.save(MultiLayerNetwork(build_conf()), step=0)
    ck = os.path.join(work, "ck")
    fstate = os.path.join(work, "fstate")
    pstate = os.path.join(work, "pstate")

    def free_port():
        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            return sk.getsockname()[1]

    router_port, status_port = free_port(), free_port()
    router_url = f"http://127.0.0.1:{router_port}"
    status_url = f"http://127.0.0.1:{status_port}"

    def get_json(url, timeout=10.0):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())

    def scrape_pipeline_counters():
        with urllib.request.urlopen(status_url + "/metrics",
                                    timeout=10) as r:
            text = r.read().decode()
        out = {}
        for line in text.splitlines():
            if line.startswith("dl4j_pipeline_") and " " in line:
                name = line.split("{", 1)[0]
                try:
                    out[name] = out.get(name, 0.0) + float(
                        line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
        return out

    fleet_cmd = [py, "-m", "deeplearning4j_tpu.cli", "fleet",
                 "-m", boot_dir, "--replicas", "2",
                 "--port", str(router_port), "--state-dir", fstate,
                 "--heartbeat-interval", "0.2",
                 "--request-timeout", "10"]

    def launch_router():
        p = subprocess.Popen(fleet_cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.DEVNULL, text=True,
                             start_new_session=True, cwd=HERE)
        for line in p.stdout:
            if line.startswith("{") and '"router"' in line:
                ann = json.loads(line)
                threading.Thread(
                    target=lambda: [None for _ in p.stdout],
                    daemon=True).start()
                return p, ann
        p.kill()
        raise RuntimeError("router never announced")

    def launch_watchdog(args):
        p = subprocess.Popen(
            [py, "-m", "deeplearning4j_tpu.cli", "watchdog",
             "--max-restarts", "4", "--backoff", "0.2", "--"] + args,
            stdout=subprocess.PIPE, text=True, cwd=HERE)
        return p

    # hammer bookkeeping: (t, ok) per request; kill windows excuse
    # failures between a kill and the first success after it
    results, kills = [], []
    hammer_stop = threading.Event()

    def hammer():
        body = json.dumps({"inputs": feats[:4].tolist()}).encode()
        while not hammer_stop.is_set():
            t = time.monotonic()
            try:
                req = urllib.request.Request(
                    router_url + "/predict", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as r:
                    ok = r.status == 200
            except Exception:
                ok = False
            results.append((t, ok))
            time.sleep(0.01)

    def watch_children(proc, sink, tag):
        """Drain a watchdog's stdout, recording child pids."""
        def run():
            for line in proc.stdout:
                if not line.startswith("{"):
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if "watchdog_child" in e:
                    sink.setdefault(tag, []).append(e["watchdog_child"])
                elif "watchdog_done" in e:
                    sink[tag + "_done"] = True
        threading.Thread(target=run, daemon=True).start()

    p_router = p_train = p_pipe = None
    replica_pids = []
    children = {}
    drill = {"kills": []}
    try:
        # ---- boot the serving side --------------------------------
        p_router, ann = launch_router()
        deadline = time.time() + 180
        while time.time() < deadline:
            if get_json(router_url + "/readyz",
                        timeout=5).get("ready_replicas", 0) >= 2:
                break
            time.sleep(0.1)
        snap = get_json(router_url + "/stats")["fleet"]
        replica_pids = sorted(r["pid"]
                              for r in snap["replicas"].values()
                              if "pid" in r)
        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()

        # ---- the controller (under its watchdog) ------------------
        p_pipe = launch_watchdog(
            ["pipeline", "--checkpoint-dir", ck,
             "--fleet-url", router_url, "--eval-data", holdout_csv,
             "--eval-threshold", "0.5", "--regression-margin", "0.25",
             "--poll-interval", "0.25", "--state-dir", pstate,
             "--status-port", str(status_port), "--name", "bench"])
        watch_children(p_pipe, children, "pipe")

        # ---- the training side (under its watchdog) ---------------
        p_train = launch_watchdog(
            ["train", "--elastic", "2", "-i", train_csv,
             "-m", conf_path, "-o", os.path.join(work, "out.ckpt"),
             "--batch-size", "8", "--epochs", "4",
             "--checkpoint-dir", ck, "--state-dir",
             os.path.join(work, "tstate"),
             "--straggler-factor", "50", "--run-timeout", "240",
             "--checkpoint-keep", "100"])
        watch_children(p_train, children, "train")

        # ---- kill 1: the elastic SUPERVISOR, first commit seen ----
        deadline = time.time() + 120
        while time.time() < deadline:
            if list_committed_steps(ck) and children.get("train"):
                chaos_mod.sigkill(children["train"][0])
                kills.append(("supervisor", time.monotonic()))
                break
            time.sleep(0.05)

        # ---- kill 2: the CONTROLLER, first promotion landed -------
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                if scrape_pipeline_counters().get(
                        "dl4j_pipeline_promotions_total", 0) >= 1 \
                        and children.get("pipe"):
                    chaos_mod.sigkill(children["pipe"][0])
                    kills.append(("controller", time.monotonic()))
                    break
            except Exception:
                pass
            time.sleep(0.1)

        # ---- kill 3: one REPLICA (fleet evicts, retries mask) -----
        time.sleep(1.0)
        if replica_pids:
            chaos_mod.sigkill(replica_pids[-1])
            kills.append(("replica", time.monotonic()))

        # ---- kill 4: the ROUTER (bench plays watchdog) ------------
        time.sleep(1.5)
        chaos_mod.sigkill(p_router.pid)
        kills.append(("router", time.monotonic()))
        p_router, ann = launch_router()

        # ---- training completes; poison steps ride the conveyor ---
        deadline = time.time() + 240
        while time.time() < deadline \
                and not children.get("train_done"):
            time.sleep(0.2)
        t_last_commit = time.monotonic()
        steps_now = list_committed_steps(ck)
        last_good = steps_now[-1] if steps_now else None
        wide = MultiLayerNetwork(build_conf(hidden=16))
        wide.fit(feats[:192],
                 np.eye(3, dtype=np.float32)[labels[:192]], epochs=40)
        with ShardedModelSaver(ck, keep=50, sync=True) as s:
            # random weights: fails the absolute gate -> quarantine
            s.save(MultiLayerNetwork(build_conf()),
                   step=(last_good or 0) + 1000)
            # trained but arch-mismatched: PASSES the eval gate, then
            # fails the canary reload -> rollback + quarantine
            s.save(wide, step=(last_good or 0) + 2000)
        poison_eval = (last_good or 0) + 1000
        poison_canary = (last_good or 0) + 2000

        # ---- convergence: newest eval-passed COMMITTED step -------
        want_key = f"{os.path.abspath(ck)}@{last_good}"
        t_converged = None
        deadline = time.time() + 240
        while time.time() < deadline:
            try:
                served = get_json(router_url + "/stats")["fleet"][
                    "checkpoints_served"]
                q1 = os.path.exists(os.path.join(
                    ck, ckfmt.step_dir_name(poison_eval),
                    QUARANTINE_MARKER))
                q2 = os.path.exists(os.path.join(
                    ck, ckfmt.step_dir_name(poison_canary),
                    QUARANTINE_MARKER))
                if list(served) == [want_key] and q1 and q2:
                    t_converged = time.monotonic()
                    break
            except Exception:
                pass
            time.sleep(0.2)
        time.sleep(1.0)  # post-convergence traffic for the audit
        hammer_stop.set()
        for t in threads:
            t.join(timeout=5)

        final_served = get_json(router_url + "/stats")["fleet"][
            "checkpoints_served"]
        counters = scrape_pipeline_counters()
        pipe_status = get_json(status_url + "/status.json").get(
            "extra", {})

        # ---- the hammer audit -------------------------------------
        def excused(t_fail):
            # the documented readmission window after each kill: until
            # the first post-kill success, and never shorter than 5 s
            # (router relaunch + capacity-gap respawn + converge)
            for _, t_k in kills:
                if t_k <= t_fail:
                    if t_fail <= t_k + 5.0:
                        return True
                    t_ok = next((t for t, ok in results
                                 if ok and t > t_k), None)
                    if t_ok is None or t_fail <= t_ok:
                        return True
            return False

        failures = [t for t, ok in results if not ok]
        unexcused = [t for t in failures if not excused(t)]
        drill.update({
            "kills": [k for k, _ in kills],
            "requests": len(results),
            "failures": len(failures),
            "failures_outside_readmission": len(unexcused),
            "champion_step": (pipe_status.get("champion") or {}).get(
                "step"),
            "last_good_step": last_good,
            "checkpoints_served": final_served,
            "quarantined": pipe_status.get("quarantined"),
            "counters": counters,
            "incarnations": {k: len(v) for k, v in children.items()
                             if isinstance(v, list)},
            "commit_to_served_s": (round(t_converged - t_last_commit,
                                         3)
                                   if t_converged else None),
        })
    finally:
        hammer_stop.set()
        for p in (p_router, p_train, p_pipe):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
        # the pipeline/train watchdog children + fleet replicas
        for pids in children.values():
            if isinstance(pids, list):
                for pid in pids:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
        for pid in replica_pids:
            try:
                os.killpg(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass

    gate_converged = bool(
        drill.get("champion_step") is not None
        and drill["champion_step"] == drill.get("last_good_step")
        and list(drill.get("checkpoints_served") or {})
        == [f"{os.path.abspath(ck)}@{drill['last_good_step']}"])
    gate_one_champion = len(drill.get("checkpoints_served") or {}) == 1
    gate_quarantine = bool(
        drill.get("quarantined")
        and len(drill["quarantined"]) >= 2
        and drill.get("counters", {}).get(
            "dl4j_pipeline_quarantines_total", 0) >= 1
        and drill.get("counters", {}).get(
            "dl4j_pipeline_rollbacks_total", 0) >= 1)
    gate_promoted = drill.get("counters", {}).get(
        "dl4j_pipeline_promotions_total", 0) >= 1
    gate_hammer = drill.get("failures_outside_readmission") == 0
    gate_all_kills = len(drill.get("kills", [])) == 4

    return {
        "value": drill.get("commit_to_served_s"),
        "unit": "s_last_commit_to_fleet_serving_it",
        "lower_is_better": True,
        "drill": drill,
        "gate_all_four_kills_fired": gate_all_kills,
        "gate_zero_errors_outside_readmission": gate_hammer,
        "gate_no_torn_promotion_one_champion": gate_one_champion,
        "gate_converged_to_newest_eval_passed": gate_converged,
        "gate_regressor_quarantined_and_rolled_back": gate_quarantine,
        "gate_promotions_scraped_live": gate_promoted,
    }


def bench_checkpoint():
    """Checkpoint subsystem config (docs/CHECKPOINTS.md): (a) the
    per-autosave STEP-LOOP STALL — blocking single-file npz writer
    (serialize+write on the caller) vs the async sharded writer (the
    caller pays only the device→host snapshot; serialize+IO overlap
    training) — the acceptance gate is async < 20% of blocking; (b)
    committed save and restore bandwidth of the sharded format; (c)
    resharded restore: the same checkpoint reassembled from its
    per-device shards onto a single device (the 8→1 topology move)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.checkpoint import (ShardedModelSaver,
                                               read_manifest,
                                               restore_network)
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

    net, batch_size = _mlp_net()
    # one tiny fit materializes updater state so checkpoints carry the
    # full production payload (params + hist + velocity)
    x_np, y_np = synthetic_mnist(batch_size)
    net.fit_scan(jnp.asarray(x_np), jnp.asarray(y_np),
                 batch_size=batch_size, epochs=1)
    _d2h(net.params())

    work = tempfile.mkdtemp(prefix="dl4j_bench_ckpt_")
    repeats = 3 if _fast() else 5
    try:
        # ---- (a) stall: blocking npz vs async sharded snapshot
        blocking = DefaultModelSaver(os.path.join(work, "block.ckpt"),
                                     keep_old=False)
        stalls_b = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            blocking.save(net)
            stalls_b.append(time.perf_counter() - t0)
        stall_blocking = statistics.median(stalls_b)

        saver = ShardedModelSaver(os.path.join(work, "sharded"),
                                  keep=2, max_in_flight=2)
        saver.save(net, iterator_position=0)  # warm the worker/dirs
        saver.flush()
        stalls_a, commits = [], []
        for i in range(repeats):
            t0 = time.perf_counter()
            saver.save(net, iterator_position=i + 1)
            stalls_a.append(time.perf_counter() - t0)
            saver.flush()  # outside the stall clock
            commits.append(time.perf_counter() - t0)
        stall_async = statistics.median(stalls_a)
        commit_s = statistics.median(commits)
        manifest = read_manifest(os.path.join(work, "sharded"))
        mb = manifest.get("total_bytes", 0) / 1e6
        saver.close()

        # ---- (b) restore bandwidth + (c) 8→1 resharded restore: the
        # shards were written per-device; restoring reassembles them and
        # places the tree on ONE device
        dev0 = jax.devices()[0]
        t0 = time.perf_counter()
        net2, _ = restore_network(os.path.join(work, "sharded"))
        net2._params = jax.device_put(net2._params, dev0)
        _d2h(net2.params())
        restore_s = time.perf_counter() - t0

        ratio = stall_async / stall_blocking if stall_blocking else None
        return {
            "value": round(stall_async * 1e3, 3), "unit": "ms/async_stall",
            "lower_is_better": True,
            "blocking_stall_ms": round(stall_blocking * 1e3, 3),
            "stall_ratio": round(ratio, 4) if ratio is not None else None,
            "stall_under_20pct": bool(ratio is not None and ratio < 0.20),
            "checkpoint_mb": round(mb, 2),
            "save_mb_s": round(mb / commit_s, 2) if commit_s else None,
            "restore_mb_s": round(mb / restore_s, 2) if restore_s else None,
            "reshard_restore_s": round(restore_s, 4),
            "n_devices": len(jax.devices()),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_telemetry():
    """Telemetry overhead config (docs/OBSERVABILITY.md): the same
    ragged iterator-driven fit as `feed` — the per-step dispatch loop is
    where the registry's counter incs / histogram observes / disabled
    spans land — run bare (registry kill switch off) vs instrumented
    (default). The delta is the whole telemetry cost of a train step;
    target <2% on the CPU smoke (asserted with a generous bound in
    tests/test_telemetry.py). Also reports registry scale and the
    /metrics render time, since scrapes run concurrently with serving.
    """
    import math

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.datasets import DeviceFeed, ListDataSetIterator
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.telemetry.exposition import render_prometheus

    net, batch_size = _mlp_net()
    n_batches = 4 if _fast() else 16
    n = batch_size * n_batches + batch_size // 3  # ragged last batch
    x_np, y_np = synthetic_mnist(n)
    feed = DeviceFeed(ListDataSetIterator(DataSet(x_np, y_np), batch_size),
                      prefetch=2)
    epochs = 1 if _fast() else 4
    steps = epochs * math.ceil(n / batch_size)

    net.fit(feed, epochs=1)  # compile every bucket program
    _d2h(net.params())

    def window_instrumented():
        net.fit(feed, epochs=epochs)
        _d2h(net.params())

    def window_bare():
        telemetry.set_enabled(False)
        try:
            net.fit(feed, epochs=epochs)
            _d2h(net.params())
        finally:
            telemetry.set_enabled(True)

    rate_off, _ = _median_rate(window_bare, steps)
    rate_on, win_s = _median_rate(window_instrumented, steps)
    ms_on, ms_off = 1000.0 / rate_on, 1000.0 / rate_off
    overhead_pct = (ms_on - ms_off) / ms_off * 100.0

    t0 = time.perf_counter()
    text = render_prometheus()
    render_ms = (time.perf_counter() - t0) * 1e3
    n_series = sum(1 for ln in text.splitlines()
                   if ln and not ln.startswith("#"))
    return {"value": round(ms_on, 4), "unit": "ms/instrumented_step",
            "lower_is_better": True,
            "bare_ms": round(ms_off, 4),
            "overhead_pct": round(overhead_pct, 2),
            "registry": {"series": n_series,
                         "render_ms": round(render_ms, 3),
                         "bytes": len(text)},
            "steps_per_window": steps, "window_s": round(win_s, 3)}


def _flash_inputs():
    import jax
    import jax.numpy as jnp

    B, H, S, D = (2, 2, 512, 64) if _fast() else (4, 8, 2048, 64)
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D), dtype=jnp.bfloat16)
    k = jax.random.normal(kk, (B, H, S, D), dtype=jnp.bfloat16)
    v = jax.random.normal(kv, (B, H, S, D), dtype=jnp.bfloat16)
    return q, k, v, (B, H, S, D)


def bench_flash():
    """Beyond-parity: Pallas flash-attention forward, compiled on the
    real chip, checked against the blockwise reference, then timed as a
    chained on-device scan. SURVEY §5 long-context."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.attention.blockwise import blockwise_attention
    from deeplearning4j_tpu.attention.flash_pallas import flash_attention

    fast = _fast()
    q, k, v, (B, H, S, D) = _flash_inputs()
    flash = lambda q, k, v: flash_attention(q, k, v, causal=True,  # noqa: E731
                                            interpret=fast)
    out = jax.block_until_ready(jax.jit(flash)(q, k, v))
    ref = blockwise_attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    if err > 0.05:  # bf16 tolerance
        raise AssertionError(f"flash vs blockwise max err {err}")

    steps = 2 if fast else 1500
    # keep-alive: scale by a tiny NON-zero constant — x*0 could legally be
    # folded to 0 by the algebraic simplifier, DCE-ing the kernel; 1e-8
    # rounds away in the bf16 add so the carry stays numerically fixed
    loop = jax.jit(lambda q, k, v: jax.lax.scan(
        lambda c, _: (q + jnp.bfloat16(1e-8) * flash(c, k, v)[0, 0, :1, :1],
                      None), q, None, length=steps)[0])
    jax.block_until_ready(loop(q, k, v))

    def window():
        _d2h(loop(q, k, v))

    rate, win_s = _median_rate(window, steps)
    ms = 1000.0 / rate
    useful_gflop = B * H * S * (S / 2) * D * 2 * 2 / 1e9  # causal fwd
    return {"value": round(ms, 4), "unit": "ms/step",
            "lower_is_better": True, "max_err_vs_blockwise": round(err, 4),
            "compiled_on": jax.devices()[0].platform,
            "shape": f"{B}x{H}x{S}x{D}",
            "tflops_useful": round(useful_gflop / ms, 1),
            "steps_per_window": steps, "window_s": round(win_s, 3)}


def bench_flash_bwd():
    """Beyond-parity: full flash-attention grad step (Pallas dQ + dK/dV
    kernels with saved-LSE recompute) as a chained on-device scan."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.attention.flash_pallas import flash_attention

    fast = _fast()
    q, k, v, (B, H, S, D) = _flash_inputs()

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, interpret=fast)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))
    steps = 2 if fast else 500

    def body(c, _):
        dq, dk, dv = grad(c, k, v)
        probe = dq[0, 0, :1, :1] + dk[0, 0, :1, :1] + dv[0, 0, :1, :1]
        # non-zero scale so the probe dependence can't be constant-folded
        return q + jnp.bfloat16(1e-8) * probe, None

    loop = jax.jit(lambda q, k, v: jax.lax.scan(
        body, q, None, length=steps)[0])
    jax.block_until_ready(loop(q, k, v))

    def window():
        _d2h(loop(q, k, v))

    rate, win_s = _median_rate(window, steps)
    return {"value": round(1000.0 / rate, 4), "unit": "ms/grad_step",
            "lower_is_better": True,
            "compiled_on": jax.devices()[0].platform,
            "shape": f"{B}x{H}x{S}x{D}",
            "steps_per_window": steps, "window_s": round(win_s, 3)}


def bench_paged_kernel():
    """Paged-attention decode kernel config (docs/SERVING.md "Decode
    kernel"). Two deterministic gates that hold on any platform: (a)
    interpret-mode parity — the REAL Pallas kernel, run through the
    interpreter, against the dense-gather path on the same evolving
    pool, teacher-forced over ragged cursors including the max_len
    window edge; (b) per-step KV read-bytes reduction — a chat-shaped
    DecodeLoop drill whose dl4j_decode_kv_read_bytes counters give the
    streamed-pages vs dense-window traffic exactly (ISSUE 13 gate:
    >= 4x). The tokens/sec win itself is a TPU-lane number — interpret
    timing is meaningless, so it is reported only when this config
    compiled on a real chip."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.attention.paged_pallas import (
        resolve_decode_kernel)
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, init_transformer_params)
    from deeplearning4j_tpu.serving.decode_loop import DecodeLoop
    from deeplearning4j_tpu.serving.paged_kv import (init_paged_pool,
                                                     paged_decode_step,
                                                     paged_prefill,
                                                     pages_for_tokens,
                                                     pages_per_slot)

    fast = _fast()
    cfg = TransformerConfig(vocab_size=512, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_len=128,
                            interpret=fast)
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    ps = 16
    rng = np.random.RandomState(0)

    # ---- (a) kernel vs gather parity on one evolving pool: ragged
    # prompts, teacher-forced steps crossing a page boundary, one slot
    # pinned AT the window edge (cursor == max_len -> trash write)
    P = pages_per_slot(cfg, ps)
    n_pages = 4 * P
    pool_g = init_paged_pool(cfg, n_pages, ps)
    trash = pool_g.trash_page
    t0s = [7, 16, 30, cfg.max_len]
    table = np.full((4, P), trash, np.int32)
    free = list(range(n_pages))
    lengths = np.asarray(t0s, np.int32)
    tb = 32
    padded = np.zeros((4, tb), np.int32)
    pids = np.full((4, tb // ps), trash, np.int32)
    for i, t in enumerate(t0s):
        pr = rng.randint(0, cfg.vocab_size, (min(t, tb),)).astype(np.int32)
        padded[i, :len(pr)] = pr
        need = pages_for_tokens(min(t, tb), ps)
        pages = [free.pop(0) for _ in range(need)]
        pids[i, :need] = pages
        table[i, :need] = pages
    # the window-edge slot owns its FULL reservation (all pages real)
    table[3] = [free.pop(0) for _ in range(P)]
    _, pool_g = paged_prefill(params, jnp.asarray(padded),
                              jnp.asarray(np.minimum(lengths, tb)),
                              pool_g, jnp.asarray(pids), cfg)
    pool_p = pool_g
    active = np.asarray([True, True, True, False])
    max_err, steps = 0.0, 4
    for _ in range(steps):
        toks = rng.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
        for i in range(4):
            if active[i]:
                pidx = lengths[i] // ps
                if table[i, pidx] == trash:
                    table[i, pidx] = free.pop(0)
        args = (jnp.asarray(toks), jnp.asarray(table),
                jnp.asarray(lengths), jnp.asarray(active))
        lg_g, pool_g = paged_decode_step(params, args[0], pool_g,
                                         args[1], args[2], args[3],
                                         cfg, kernel="gather")
        lg_p, pool_p = paged_decode_step(params, args[0], pool_p,
                                         args[1], args[2], args[3],
                                         cfg, kernel="pallas")
        max_err = max(max_err, float(jnp.max(jnp.abs(lg_p - lg_g))))
        lengths = lengths + np.where(active, 1, 0).astype(np.int32)
    if max_err > 1e-5:
        raise AssertionError(
            f"pallas vs gather decode max err {max_err}")

    # ---- (b) chat-shaped KV traffic drill: short live contexts inside
    # wide max_len reservations — exactly where the dense gather
    # over-reads. The loop books BOTH lane figures every dispatch, so
    # the gather lane (CPU smoke) measures the identical reduction the
    # kernel lane realizes on-chip.
    n_streams = 8
    loop = DecodeLoop(params, cfg, slots=n_streams, page_size=ps,
                      horizon=4)
    prompts = [rng.randint(0, cfg.vocab_size,
                           (int(rng.choice([8, 16])),)).astype(np.int32)
               for _ in range(n_streams)]
    streams = [loop.submit(p, 16) for p in prompts]
    for s in streams:
        s.result(240)
    snap = loop.snapshot()
    loop.close()
    kv = snap["decode_kernel"]["kv_read_bytes"]
    reduction = kv["gather"] / kv["kernel"]
    return {"value": round(reduction, 2), "unit": "x_kv_read_reduction",
            "gate_4x": bool(reduction >= 4.0),
            "parity_max_err": round(max_err, 9),
            "parity_steps": steps,
            "kernel_read_bytes": kv["kernel"],
            "gather_read_bytes": kv["gather"],
            "path_selected": snap["decode_kernel"]["selected"],
            "auto_resolves_to": resolve_decode_kernel("auto", cfg, ps),
            "interpret_parity": fast,
            "tokens_per_sec": None if fast else "tpu_lane",
            "compiled_on": jax.devices()[0].platform,
            "n_streams": n_streams, "page_size": ps,
            "pages_per_slot": pages_per_slot(cfg, ps)}


def bench_warmup():
    """AOT warm start (docs/WARMUP.md): spawn `cli serve
    --compile-cache DIR --warmup-plan auto` replica processes against
    ONE cache directory — cold (empty cache: compile + persist + record
    the plan) then warm (plan replay: AOT loads, zero compiles) — and
    gate the subsystem's contract:

    - warm warmup_seconds (the /readyz-gating phase: socket-open to
      ready) >= 3x faster than cold;
    - warm boot reports recompiled_after_warmup == 0 on /stats with
      cache hits scraped LIVE off /metrics;
    - chaos leg: a replica with compile.cache_read faulted at every
      ordinal still reaches ready and serves correct predictions
      (cold-compile fallback, zero request errors);
    - trainer leg: cold-vs-warm first `fit()` wall in fresh
      subprocesses riding the same store.

    Spawn-to-ready wall is recorded too, but the gate rides the warmup
    phase: interpreter + jax import (identical both ways) would
    otherwise drown the signal on the CPU smoke."""
    import json as _json
    import shutil
    import tempfile
    import urllib.request

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver
    from deeplearning4j_tpu.serving.fleet import ReplicaSpawner
    from deeplearning4j_tpu.testing import chaos

    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(16).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([32])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=4)
            .pretrain(False).build())
    work = tempfile.mkdtemp(prefix="dl4j_bench_warmup_")
    ckpt = os.path.join(work, "warm.ckpt")
    cache = os.path.join(work, "compile_cache")
    DefaultModelSaver(ckpt, keep_old=False).save(MultiLayerNetwork(conf))
    body = _json.dumps(
        {"inputs": np.random.RandomState(0).rand(4, 16).tolist()}
    ).encode()

    def _get(url, timeout=10):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()

    def boot(extra_env=None):
        """Spawn one replica; returns its measurements and kills it."""
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        spawner = ReplicaSpawner(
            ckpt, env=env,
            serve_args=["--compile-cache", cache, "--warmup-plan",
                        "auto", "--max-delay-ms", "1"])
        t0 = time.perf_counter()
        proc, url = spawner.spawn()
        try:
            ready = None
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                try:
                    status, raw = _get(url + "/readyz", timeout=5)
                    if status == 200:
                        ready = _json.loads(raw)
                        break
                except Exception:  # noqa: BLE001 — 503 until warm
                    pass
                time.sleep(0.05)
            wall = time.perf_counter() - t0
            if ready is None:
                raise RuntimeError("replica never became ready")
            errors = 0
            for _ in range(8):
                try:
                    req = urllib.request.Request(
                        url + "/predict", data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=30) as r:
                        out = _json.loads(r.read())
                    if len(out["outputs"]) != 4:
                        errors += 1
                except Exception:  # noqa: BLE001
                    errors += 1
            _, stats_raw = _get(url + "/stats", timeout=30)
            stats = _json.loads(stats_raw)
            _, metrics_raw = _get(url + "/metrics", timeout=30)
            scraped = {}
            for line in metrics_raw.decode().splitlines():
                for name in ("dl4j_compile_cache_hits_total",
                             "dl4j_compile_cache_misses_total"):
                    if line.startswith(name + " "):
                        scraped[name] = float(line.split()[-1])
            return {"spawn_to_ready_s": round(wall, 3),
                    "warmup_s": ready.get("warmup_seconds"),
                    "warmup": stats.get("warmup"),
                    "compile_cache": stats.get("compile_cache"),
                    "metrics": scraped,
                    "predict_errors": errors}
        finally:
            proc.kill()
            proc.wait(timeout=30)

    try:
        cold = boot()
        warm = boot()
        chaotic = boot(chaos.env_spec(
            [chaos.Rule("compile.cache_read", "error")], seed=0))

        ratio = (cold["warmup_s"] / warm["warmup_s"]
                 if cold["warmup_s"] and warm["warmup_s"] else None)
        warm_hits = warm["metrics"].get(
            "dl4j_compile_cache_hits_total", 0.0)
        recompiled = (warm.get("warmup") or {}).get(
            "recompiled_after_warmup")

        # trainer leg: first fit() in a fresh process, cold vs warm
        train_cache = os.path.join(work, "train_cache")
        script = (
            "import sys,time,numpy as np\n"
            "from deeplearning4j_tpu import compilecache as cc\n"
            "from deeplearning4j_tpu.config import "
            "NeuralNetConfiguration\n"
            "from deeplearning4j_tpu.nn.multilayer import "
            "MultiLayerNetwork\n"
            "conf=(NeuralNetConfiguration.builder().lr(0.1).n_in(16)"
            ".activation_function('tanh')"
            ".optimization_algo('iteration_gradient_descent')"
            ".num_iterations(1).use_adagrad(False).list(2)"
            ".hidden_layer_sizes([32])"
            ".override(1,layer='output',loss_function='mcxent',"
            "activation_function='softmax',n_out=4)"
            ".pretrain(False).build())\n"
            "cc.activate(sys.argv[1])\n"
            "x=np.random.RandomState(0).rand(32,16).astype('float32')\n"
            "y=np.eye(4,dtype='float32')"
            "[np.random.RandomState(1).randint(0,4,32)]\n"
            "t0=time.perf_counter()\n"
            "MultiLayerNetwork(conf).fit(x,y,epochs=1)\n"
            "print('FIT_S', time.perf_counter()-t0)\n"
            "print('HITS', cc.stats()['hits'])\n")

        def run_fit():
            import sys

            env = dict(os.environ)
            env["PYTHONPATH"] = HERE + os.pathsep + env.get(
                "PYTHONPATH", "")
            out = subprocess.run(
                [sys.executable, "-c", script, train_cache],
                capture_output=True, text=True, timeout=300, env=env)
            vals = dict(line.split() for line in out.stdout.splitlines()
                        if line.startswith(("FIT_S", "HITS")))
            return float(vals["FIT_S"]), int(vals["HITS"])

        fit_cold_s, _ = run_fit()
        fit_warm_s, fit_warm_hits = run_fit()

        return {
            "value": round(ratio, 2) if ratio else None,
            "unit": "x_warmup_speedup",
            "gate_3x": bool(ratio and ratio >= 3.0),
            "gate_zero_recompiles": recompiled == 0,
            "gate_live_hits": bool(warm_hits >= 1),
            "gate_chaos_clean": bool(
                chaotic["predict_errors"] == 0),
            "cold": cold, "warm": warm, "chaos": chaotic,
            "trainer": {"cold_fit_s": round(fit_cold_s, 3),
                        "warm_fit_s": round(fit_warm_s, 3),
                        "warm_hits": fit_warm_hits},
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


CONFIGS = {
    "mlp": bench_mlp,
    "feed": bench_feed,
    "guardian": bench_guardian,
    "serve": bench_serve,
    "prefix_cache": bench_prefix_cache,
    "speculative": bench_speculative,
    "fleet": bench_fleet,
    "chaos": bench_chaos,
    "warmup": bench_warmup,
    "stream_failover": bench_stream_failover,
    "fleet_prefix": bench_fleet_prefix,
    "disagg": bench_disagg,
    "slo_tiers": bench_slo_tiers,
    "train_elastic": bench_train_elastic,
    "controlplane": bench_controlplane,
    "pipeline": bench_pipeline,
    "checkpoint": bench_checkpoint,
    "telemetry": bench_telemetry,
    "lenet": bench_lenet,
    "dbn": bench_dbn,
    "word2vec": bench_word2vec,
    "glove": bench_glove,
    "flash": bench_flash,
    "flash_bwd": bench_flash_bwd,
    "paged_kernel": bench_paged_kernel,
}

METRIC_NAMES = {
    "mlp": "mlp_mnist_train_samples_per_sec_per_chip",
    "feed": "device_feed_ragged_stream_steps_per_sec",
    "guardian": "guardian_guarded_step_time_ms",
    "serve": "serving_decode_tokens_per_sec_cached",
    "prefix_cache": "serving_prefix_cache_prefill_token_reduction",
    "speculative": "serving_speculative_tokens_per_dispatch_speedup",
    "fleet": "fleet_predict_rows_per_sec_4_replicas",
    "chaos": "chaos_sigstop_breaker_eviction_s",
    "warmup": "serving_warm_boot_warmup_speedup",
    "stream_failover": "serving_stream_failover_p99_ttnt_ms",
    "fleet_prefix": "fleet_prefix_prefill_token_reduction",
    "disagg": "serving_disagg_decode_p99_under_prefill_storm_ms",
    "slo_tiers": "serving_interactive_p99_under_batch_flood_ms",
    "train_elastic": "train_elastic_kill_recovery_s",
    "controlplane": "controlplane_router_restart_recovery_s",
    "pipeline": "pipeline_commit_to_served_s",
    "checkpoint": "checkpoint_async_save_stall_ms",
    "telemetry": "telemetry_instrumented_step_time_ms",
    "lenet": "lenet_mnist_step_time_ms",
    "dbn": "dbn_pretrain_finetune_samples_per_sec_per_chip",
    "word2vec": "word2vec_skipgram_pairs_per_sec",
    "glove": "glove_training_triples_per_sec",
    "flash": "flash_attention_causal_step_time_ms",
    "flash_bwd": "flash_attention_grad_step_time_ms",
    "paged_kernel": "serving_decode_kv_read_bytes_reduction",
}


# ----------------------------------------------------------------- history
def _load_history():
    try:
        with open(HIST_PATH) as f:
            hist = json.load(f)
    except (OSError, ValueError):
        hist = {}
    if hist.get("protocol") != PROTOCOL:
        # protocol change invalidates every pin: archive, start fresh
        hist = {"protocol": PROTOCOL,
                "baselines": {},
                "baselines_v1": hist.get("baselines", {}),
                "runs": hist.get("runs", [])[-20:]}
    if any(not isinstance(v, dict)
           for v in hist.get("baselines", {}).values()):
        hist["baselines"] = {}  # migrate flat pins (pre-platform-scoping)
    return hist


def _write_history(hist) -> None:
    try:
        with open(HIST_PATH, "w") as f:
            json.dump(hist, f, indent=1)
    except OSError:
        pass


def _summary_line(results) -> str:
    primary_name = "mlp" if "mlp" in results else next(iter(results), None)
    primary = results.get(primary_name, {})
    summary = {
        "metric": METRIC_NAMES.get(primary_name, primary_name or "none"),
        "value": primary.get("value"),
        "unit": primary.get("unit"),
        # null (not 1.0) when the primary config errored or was skipped —
        # a neutral ratio for a missing measurement would mislead gating
        "vs_baseline": primary.get("vs_baseline"),
        "protocol": PROTOCOL,
        "extra": {k: v for k, v in results.items() if k != primary_name},
    }
    for key in ("error", "skipped"):  # surface WHY the primary is null
        if key in primary:
            summary[key] = primary[key]
    return json.dumps(summary)


def main() -> None:
    import jax

    selected = os.environ.get("BENCH_CONFIGS")
    names = ([n.strip() for n in selected.split(",") if n.strip()]
             if selected else list(CONFIGS))
    budget = float(os.environ.get("BENCH_BUDGET_S", "720"))
    # 720 s: a bad-weather full run measured 523 s of work — a 480 s
    # budget would have skipped the flash configs it was protecting

    hist = _load_history()
    run_entry = {"ts": time.time(), "protocol": PROTOCOL,
                 "platform": jax.devices()[0].platform, "results": {}}
    try:
        run_entry["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, cwd=HERE).stdout.strip()
    except OSError:
        run_entry["commit"] = ""
    hist["runs"].append(run_entry)
    hist["runs"] = hist["runs"][-50:]

    start = time.monotonic()
    results = {}
    for name in names:
        if results and time.monotonic() - start > budget:
            results[name] = {"skipped": f"BENCH_BUDGET_S={budget:g} spent"}
            run_entry["results"][name] = results[name]
            _write_history(hist)
            print(_summary_line(results), flush=True)
            continue
        try:
            res = CONFIGS[name]()
        except Exception as e:  # a broken config must not hide the others
            res = {"error": f"{type(e).__name__}: {e}"}
        if res.get("value") is not None:
            # pins are per-platform: a CPU smoke run must never pin (or be
            # compared against) the TPU baselines the driver records
            platform = run_entry["platform"]
            pins = hist["baselines"].setdefault(platform, {})
            base = pins.get(name)
            if base is None:
                pins[name] = res["value"]
                base = res["value"]
            ratio = res["value"] / base
            if res.get("lower_is_better"):
                ratio = base / res["value"]
            res["vs_baseline"] = round(ratio, 4)
            # between-process spread recorded at pin time (BASELINE.md):
            # a vs_baseline inside the pin's spread band is tunnel
            # weather, not signal
            spread = hist.get("pin_info", {}).get("spread", {}).get(name)
            if spread and platform == "tpu":
                res["pin_spread"] = spread
        results[name] = res
        run_entry["results"][name] = res
        _write_history(hist)
        print(_summary_line(results), flush=True)


if __name__ == "__main__":
    main()
