"""Benchmark harness: BASELINE.md configs, repeat-median, pinned baselines.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The primary metric stays BASELINE config 1 (MNIST 3-layer MLP samples/sec/
chip); "extra" carries the other measured configs (LeNet-MNIST step time,
DBN pretrain+finetune, Word2Vec throughput) each as
{value, unit, vs_baseline}.

Noise control: every config is timed REPEATS times after a compile warm-up
and the median is reported. vs_baseline compares against a *pinned*
baseline in BENCH_HISTORY.json — recorded the first time a metric is ever
measured and never overwritten by later runs (history appends instead), so
the comparison point cannot drift with run-to-run noise. Re-pin by
deleting the metric from the "baselines" dict.

Select a subset with BENCH_CONFIGS=mlp,lenet (default: all).
"""

import json
import os
import statistics
import subprocess
import time

import numpy as np

REPEATS = 3
HERE = os.path.dirname(os.path.abspath(__file__))
HIST_PATH = os.path.join(HERE, "BENCH_HISTORY.json")


def _median_time(fn, repeats=REPEATS):
    """Median wall time of fn() over `repeats` runs (fn blocks until ready)."""
    times = []
    for _ in range(repeats):
        start = time.perf_counter()
        fn()
        times.append(time.perf_counter() - start)
    return statistics.median(times)


# ----------------------------------------------------------------- configs
def bench_mlp():
    """BASELINE config 1: MNIST 3-layer MLP, samples/sec/chip."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 4096
    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).n_in(784).activation_function("relu")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(3)
            .hidden_layer_sizes([2048, 1024])
            .override(2, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=10)
            .pretrain(False)
            .build())
    net = MultiLayerNetwork(conf)
    x_np, y_np = synthetic_mnist(batch_size)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    net.fit(x, y)  # compile
    jax.block_until_ready(net.params())

    steps = 50

    def run():
        for _ in range(steps):
            net.fit(x, y)
        jax.block_until_ready(net.params())

    elapsed = _median_time(run)
    value = steps * batch_size / elapsed / max(1, len(jax.devices()))
    return {"value": round(value, 2), "unit": "samples/sec/chip"}


def bench_lenet():
    """BASELINE config 2: LeNet-5-style CNN on MNIST, per-step time (the
    north-star named in BASELINE.md). Reference path:
    core/nn/layers/convolution/ConvolutionDownSampleLayer.java:52."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.preprocessors import (
        ConvolutionInputPreProcessor, ConvolutionPostProcessor)
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 1024
    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).activation_function("relu")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(4)
            .override(0, layer="conv", filter_size=[5, 5], stride=[2, 2],
                      num_in_feature_maps=1, num_feature_maps=6)
            .override(1, layer="conv", filter_size=[5, 5], stride=[2, 2],
                      num_in_feature_maps=6, num_feature_maps=16)
            .override(2, layer="dense", n_in=4 * 4 * 16, n_out=120)
            .override(3, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_in=120, n_out=10)
            .input_preprocessor(0, ConvolutionInputPreProcessor(28, 28, 1))
            .input_preprocessor(2, ConvolutionPostProcessor())
            .pretrain(False)
            .build())
    net = MultiLayerNetwork(conf)
    x_np, y_np = synthetic_mnist(batch_size)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    net.fit(x, y)  # compile
    jax.block_until_ready(net.params())

    steps = 30

    def run():
        for _ in range(steps):
            net.fit(x, y)
        jax.block_until_ready(net.params())

    elapsed = _median_time(run)
    return {"value": round(elapsed / steps * 1000, 3), "unit": "ms/step",
            "lower_is_better": True, "batch_size": batch_size}


def bench_dbn():
    """BASELINE config 4: DBN (RBM stack) pretrain + finetune,
    samples/sec/chip over the whole pretrain+finetune pass. Reference path:
    core/models/featuredetectors/rbm/RBM.java:105 +
    nn/multilayer/MultiLayerNetwork.java:142."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 2048
    iters = 5  # pretrain + finetune iterations per fit() call

    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).n_in(784).activation_function("sigmoid")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(iters)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(3)
            .hidden_layer_sizes([1024, 512])
            .override(0, layer="rbm", k=1)
            .override(1, layer="rbm", k=1)
            .override(2, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=10)
            .pretrain(True)
            .build())
    net = MultiLayerNetwork(conf)

    x_np, y_np = synthetic_mnist(batch_size)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    # warm-up compiles every phase; fit() re-runs pretrain+finetune on each
    # call and the net caches its compiled pretrain/train steps, so timed
    # repeats measure throughput, not XLA compilation
    net.fit(x, y)
    jax.block_until_ready(net.params())

    def run():
        net.fit(x, y)
        jax.block_until_ready(net.params())

    elapsed = _median_time(run)
    # samples processed = batch * iters * (pretrain layers + finetune)
    processed = batch_size * iters * 3
    value = processed / elapsed / max(1, len(jax.devices()))
    return {"value": round(value, 2), "unit": "samples/sec/chip"}


def bench_word2vec():
    """BASELINE config 3 shape: Word2Vec skip-gram throughput (training
    pairs/sec) on a synthetic zipfian corpus (text8 needs egress; the hot
    path — pair mining + jitted HS/negative-sampling step — is identical).
    Reference path: nlp/models/word2vec/Word2Vec.java:101,
    InMemoryLookupTable.java:188."""
    import jax

    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.RandomState(0)
    vocab = [f"w{i}" for i in range(2000)]
    zipf = 1.0 / np.arange(1, len(vocab) + 1)
    probs = zipf / zipf.sum()
    n_tokens = 200_000
    tokens = rng.choice(len(vocab), size=n_tokens, p=probs)
    sentences = [" ".join(vocab[t] for t in tokens[i:i + 40])
                 for i in range(0, n_tokens, 40)]

    w2v = Word2Vec(sentences, layer_size=128, window=5,
                   min_word_frequency=1, negative=5, iterations=1,
                   seed=0)
    w2v.fit()  # warm-up: builds vocab + compiles the jitted step
    rates = []
    for _ in range(REPEATS):
        start = time.perf_counter()
        w2v.fit()  # re-mines + retrains with the cached compiled step
        rates.append(w2v.pairs_trained / (time.perf_counter() - start))
    return {"value": round(statistics.median(rates), 2), "unit": "pairs/sec"}


def bench_flash():
    """Beyond-parity: the Pallas flash-attention kernel COMPILED on the
    real chip (not interpret mode), checked against the blockwise
    reference implementation, then timed. SURVEY §5 long-context."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.attention.blockwise import blockwise_attention
    from deeplearning4j_tpu.attention.flash_pallas import flash_attention

    on_tpu = jax.devices()[0].platform == "tpu"
    B, H, S, D = 4, 8, 2048, 64
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D), dtype=jnp.bfloat16)
    k = jax.random.normal(kk, (B, H, S, D), dtype=jnp.bfloat16)
    v = jax.random.normal(kv, (B, H, S, D), dtype=jnp.bfloat16)

    flash = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=not on_tpu))
    out = jax.block_until_ready(flash(q, k, v))  # compile + run
    ref = blockwise_attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    if err > 0.05:  # bf16 tolerance
        raise AssertionError(f"flash vs blockwise max err {err}")

    steps = 20

    def run():
        for _ in range(steps):
            o = flash(q, k, v)
        jax.block_until_ready(o)

    elapsed = _median_time(run)
    return {"value": round(elapsed / steps * 1000, 3), "unit": "ms/step",
            "lower_is_better": True, "max_err_vs_blockwise": round(err, 4),
            "compiled_on": jax.devices()[0].platform,
            "shape": f"{B}x{H}x{S}x{D}"}


CONFIGS = {
    "mlp": bench_mlp,
    "lenet": bench_lenet,
    "dbn": bench_dbn,
    "word2vec": bench_word2vec,
    "flash": bench_flash,
}

METRIC_NAMES = {
    "mlp": "mlp_mnist_train_samples_per_sec_per_chip",
    "lenet": "lenet_mnist_step_time_ms",
    "dbn": "dbn_pretrain_finetune_samples_per_sec_per_chip",
    "word2vec": "word2vec_skipgram_pairs_per_sec",
    "flash": "flash_attention_causal_step_time_ms",
}


# ----------------------------------------------------------------- history
def _load_history():
    try:
        with open(HIST_PATH) as f:
            hist = json.load(f)
    except (OSError, ValueError):
        hist = {}
    # migrate the old single-value format {"value": v, "ts": t}
    if "baselines" not in hist:
        old = hist.get("value")
        hist = {"baselines": {}, "runs": []}
        if old:
            hist["baselines"]["mlp"] = old
    return hist


def main() -> None:
    import jax

    selected = os.environ.get("BENCH_CONFIGS")
    names = ([n.strip() for n in selected.split(",") if n.strip()]
             if selected else list(CONFIGS))

    hist = _load_history()
    results = {}
    for name in names:
        try:
            results[name] = CONFIGS[name]()
        except Exception as e:  # a broken config must not hide the others
            results[name] = {"error": f"{type(e).__name__}: {e}"}

    for name, res in results.items():
        if "error" in res:
            continue
        base = hist["baselines"].get(name)
        if base is None:
            hist["baselines"][name] = res["value"]
            base = res["value"]
        ratio = res["value"] / base
        if res.get("lower_is_better"):
            ratio = base / res["value"]
        res["vs_baseline"] = round(ratio, 4)

    try:
        commit = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                                capture_output=True, text=True,
                                cwd=HERE).stdout.strip()
    except OSError:
        commit = ""
    hist["runs"].append({"ts": time.time(), "commit": commit,
                         "platform": jax.devices()[0].platform,
                         "results": results})
    hist["runs"] = hist["runs"][-50:]
    try:
        with open(HIST_PATH, "w") as f:
            json.dump(hist, f, indent=1)
    except OSError:
        pass

    primary_name = "mlp" if "mlp" in results else next(iter(results), None)
    primary = results.get(primary_name, {})
    print(json.dumps({
        "metric": METRIC_NAMES.get(primary_name, primary_name or "none"),
        "value": primary.get("value"),
        "unit": primary.get("unit"),
        "vs_baseline": primary.get("vs_baseline", 1.0),
        "extra": {k: v for k, v in results.items() if k != primary_name},
    }))


if __name__ == "__main__":
    main()
