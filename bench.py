"""Benchmark: samples/sec/chip for MultiLayerNetwork.fit-equivalent training.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
BASELINE config 1: MNIST 3-layer MLP (BASELINE.md — the reference publishes no
numbers; vs_baseline compares to the last value recorded in BENCH_HISTORY.json
when present, else 1.0).
"""

import json
import os
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch_size = 4096
    conf = (NeuralNetConfiguration.builder()
            .lr(0.05).n_in(784).activation_function("relu")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1)
            .batch_size(batch_size)
            .compute_dtype("bfloat16")
            .list(3)
            .hidden_layer_sizes([2048, 1024])
            .override(2, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=10)
            .pretrain(False)
            .build())
    net = MultiLayerNetwork(conf)

    x_np, y_np = synthetic_mnist(batch_size)
    x, y = jnp.asarray(x_np), jnp.asarray(y_np)

    # Warm up (compile)
    net.fit(x, y)
    jax.block_until_ready(net.params())

    steps = 50
    start = time.perf_counter()
    for _ in range(steps):
        net.fit(x, y)
    jax.block_until_ready(net.params())
    elapsed = time.perf_counter() - start

    samples_per_sec = steps * batch_size / elapsed
    n_chips = max(1, len(jax.devices()))
    value = samples_per_sec / n_chips

    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_HISTORY.json")
    vs_baseline = 1.0
    try:
        with open(hist_path) as f:
            hist = json.load(f)
        if hist.get("value"):
            vs_baseline = value / hist["value"]
    except (OSError, ValueError):
        hist = None
    try:
        with open(hist_path, "w") as f:
            json.dump({"value": value, "ts": time.time()}, f)
    except OSError:
        pass

    print(json.dumps({
        "metric": "mlp_mnist_train_samples_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
