"""End-to-end HTTP serving: /predict, /generate, /healthz, /stats on an
ephemeral port; graceful shutdown releases the socket (the shared
utils/httpd.py lifecycle both this server and plot/render_server use);
CLI `serve` smoke."""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.config import NeuralNetConfiguration
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_transformer_params)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import InferenceEngine, serve_network

CFG = TransformerConfig(vocab_size=17, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=64, interpret=True)


def _net(n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(n_in).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=n_out)
            .pretrain(False).build())
    return MultiLayerNetwork(conf)


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class TestHTTPRoundTrip:
    def test_predict_healthz_stats_and_shutdown(self):
        net = _net()
        handle = serve_network(net, n_replicas=2, max_batch_size=16,
                               max_delay_ms=1.0, warmup_shape=(4,))
        try:
            assert handle.port != 0  # ephemeral port was bound
            health = _get(f"{handle.url}/healthz")
            assert health["ok"] and health["replicas"] == 2

            x = np.random.RandomState(0).rand(3, 4)
            out = _post(f"{handle.url}/predict",
                        {"inputs": x.tolist()})
            assert np.asarray(out["outputs"]).shape == (3, 3)
            assert len(out["classes"]) == 3
            ref = np.asarray(net.output(x.astype(np.float32)))
            np.testing.assert_allclose(np.asarray(out["outputs"]), ref,
                                       atol=1e-5)

            stats = _get(f"{handle.url}/stats")
            assert stats["replicas"]["rows"] >= 3
            assert stats["batcher"]["completed"] >= 1
            assert stats["batcher"]["queue_depth"] >= 0
            # per-bucket forward counts (3 rows -> the 8-bucket)
            assert sum(stats["replicas"]["bucket_forwards"].values()) >= 1
            assert stats["uptime_s"] >= 0
        finally:
            handle.close()

    def test_metrics_e2e_scrape(self):
        """Acceptance bar: a /metrics scrape on a live serve instance
        returns Prometheus text carrying train/serve/guardian/device
        series (docs/OBSERVABILITY.md)."""
        net = _net()
        with serve_network(net, n_replicas=1, max_batch_size=16,
                           max_delay_ms=1.0) as handle:
            x = np.random.RandomState(0).rand(2, 4)
            _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            with urllib.request.urlopen(f"{handle.url}/metrics",
                                        timeout=30) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            for series in (
                    "dl4j_serve_requests_total",      # serve
                    "dl4j_serve_latency_seconds_bucket",
                    "dl4j_serve_bucket_forwards_total",
                    "dl4j_batcher_queue_depth",
                    "dl4j_train_steps_total",         # train
                    "dl4j_guardian_events_total",     # guardian
                    "dl4j_device_count",              # device
                    "dl4j_device_memory_bytes",
                    "dl4j_jit_programs",
            ):
                assert series in text, f"{series} missing from /metrics"
            # this serve instance's engine actually counted the request
            assert 'dl4j_serve_requests_total{engine="' in text
            snap = _get(f"{handle.url}/snapshot")
            assert "dl4j_serve_requests" in snap
        # socket actually released: reconnect must fail fast
        with pytest.raises((ConnectionError, urllib.error.URLError, OSError)):
            _get(f"{handle.url}/healthz", timeout=2)
        # and the port is rebindable (server_close ran)
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", handle.port))

    def test_generate_endpoint(self):
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen) as handle:
            prompt = [[1, 2, 3, 4]]
            out = _post(f"{handle.url}/generate",
                        {"prompt": prompt, "n_tokens": 5})
            toks = np.asarray(out["tokens"])
            assert toks.shape == (1, 9)
            assert (toks[:, :4] == np.asarray(prompt)).all()
            assert ((0 <= toks) & (toks < CFG.vocab_size)).all()

    def test_error_paths(self):
        with serve_network(_net(), n_replicas=1,
                           max_delay_ms=1.0) as handle:
            # bad JSON -> 400
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/predict", {"nope": 1})
            assert e.value.code == 400
            # feature-width mismatch surfaces as a request error
            with pytest.raises(urllib.error.HTTPError):
                _post(f"{handle.url}/predict",
                      {"inputs": [[1.0, 2.0]]})
            # /generate without a transformer engine -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/generate",
                      {"prompt": [[1]], "n_tokens": 2})
            assert e.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{handle.url}/nowhere")
            assert e.value.code == 404


class TestHotReload:
    """ISSUE satellite: POST /reload hot-swaps replica weights from a
    checkpoint path without dropping in-flight requests."""

    def _checkpoints(self, tmp_path):
        """Two nets with the same architecture but different weights,
        each checkpointed: (net_a, net_b, sharded_dir_b, npz_path_b)."""
        from deeplearning4j_tpu.checkpoint import ShardedModelSaver
        from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

        net_a, net_b = _net(), _net()
        x, y = (np.random.RandomState(1).rand(48, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[
                    np.random.RandomState(2).randint(0, 3, 48)])
        net_b.fit(x, y, epochs=3)  # diverge the weights
        sharded = str(tmp_path / "sharded")
        with ShardedModelSaver(sharded, sync=True) as saver:
            saver.save(net_b, iterator_position=3)
        npz = str(tmp_path / "b.ckpt")
        DefaultModelSaver(npz, keep_old=False).save(net_b)
        return net_a, net_b, sharded, npz

    def test_reload_swaps_weights_without_dropping_requests(self,
                                                            tmp_path):
        import threading

        net_a, net_b, sharded, _ = self._checkpoints(tmp_path)
        x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        ref_a = np.asarray(net_a.output(x))
        ref_b = np.asarray(net_b.output(x))
        assert not np.allclose(ref_a, ref_b)  # the swap is observable

        with serve_network(net_a, n_replicas=2, max_batch_size=16,
                           max_delay_ms=1.0, warmup_shape=(4,)) as handle:
            out = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["outputs"]), ref_a,
                                       atol=1e-5)

            # hammer /predict from the side WHILE reloading: every
            # response must be valid (old or new weights, never an error)
            stop = threading.Event()
            failures = []

            def hammer():
                while not stop.is_set():
                    try:
                        r = _post(f"{handle.url}/predict",
                                  {"inputs": x.tolist()})
                        got = np.asarray(r["outputs"])
                        if not (np.allclose(got, ref_a, atol=1e-5)
                                or np.allclose(got, ref_b, atol=1e-5)):
                            failures.append("torn outputs")
                    except Exception as e:  # noqa: BLE001
                        failures.append(repr(e))

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            try:
                res = _post(f"{handle.url}/reload", {"path": sharded})
            finally:
                stop.set()
                t.join(timeout=30)
            assert res["reloaded"] and res["replicas"] == 2
            assert res["step"] == 3
            assert failures == []

            # all replicas now serve net_b's weights
            out2 = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out2["outputs"]), ref_b,
                                       atol=1e-5)
            stats = _get(f"{handle.url}/stats")
            assert stats["last_reload"]["step"] == 3

    def test_reload_accepts_legacy_npz_checkpoints(self, tmp_path):
        net_a, net_b, _, npz = self._checkpoints(tmp_path)
        x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        ref_b = np.asarray(net_b.output(x))
        with serve_network(net_a, n_replicas=1,
                           max_delay_ms=1.0) as handle:
            _post(f"{handle.url}/reload", {"path": npz})
            out = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["outputs"]), ref_b,
                                       atol=1e-5)

    def test_reload_error_paths(self, tmp_path):
        net_a, _, sharded, npz = self._checkpoints(tmp_path)
        with serve_network(net_a, n_replicas=1,
                           max_delay_ms=1.0) as handle:
            # missing path key -> 400
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload", {})
            assert e.value.code == 400
            # nonexistent checkpoint -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload",
                      {"path": str(tmp_path / "nope")})
            assert e.value.code == 404
            # step pin against a single-file npz -> 400, not a silent
            # load of whatever the file holds
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload", {"path": npz, "step": 5})
            assert e.value.code == 400
            assert "no steps" in json.loads(e.value.read())["error"]
            # architecture mismatch -> 400 naming the leaf
            from deeplearning4j_tpu.checkpoint import ShardedModelSaver
            other_conf = (NeuralNetConfiguration.builder()
                          .lr(0.1).n_in(4).activation_function("tanh")
                          .optimization_algo("iteration_gradient_descent")
                          .num_iterations(1).use_adagrad(False)
                          .list(2).hidden_layer_sizes([16])
                          .override(1, layer="output",
                                    loss_function="mcxent",
                                    activation_function="softmax",
                                    n_out=3)
                          .pretrain(False).build())
            wide = MultiLayerNetwork(other_conf)
            wrong = str(tmp_path / "wrong")
            with ShardedModelSaver(wrong, sync=True) as saver:
                saver.save(wide)
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload", {"path": wrong})
            assert e.value.code == 400
            body = json.loads(e.value.read())
            assert "0/W" in body["error"]  # names the mismatched leaf
            # the serving weights are untouched after the failed reload
            x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
            out = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["outputs"]),
                                       np.asarray(net_a.output(x)),
                                       atol=1e-5)


class TestCLIServe:
    def test_serve_smoke(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import main
        from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

        ckpt = str(tmp_path / "m.ckpt")
        DefaultModelSaver(ckpt).save(_net())
        assert main(["serve", "-m", ckpt, "--replicas", "1",
                     "--max-delay-ms", "1", "--smoke"]) == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["serving"].startswith("http://127.0.0.1:")
        assert out["replicas"] == 1
