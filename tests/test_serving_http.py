"""End-to-end HTTP serving: /predict, /generate, /healthz, /stats on an
ephemeral port; graceful shutdown releases the socket (the shared
utils/httpd.py lifecycle both this server and plot/render_server use);
CLI `serve` smoke."""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.config import NeuralNetConfiguration
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   init_transformer_params)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import InferenceEngine, serve_network

CFG = TransformerConfig(vocab_size=17, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_len=64, interpret=True)


def _net(n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder()
            .lr(0.1).n_in(n_in).activation_function("tanh")
            .optimization_algo("iteration_gradient_descent")
            .num_iterations(1).use_adagrad(False)
            .list(2).hidden_layer_sizes([8])
            .override(1, layer="output", loss_function="mcxent",
                      activation_function="softmax", n_out=n_out)
            .pretrain(False).build())
    return MultiLayerNetwork(conf)


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class TestHTTPRoundTrip:
    def test_predict_healthz_stats_and_shutdown(self):
        net = _net()
        handle = serve_network(net, n_replicas=2, max_batch_size=16,
                               max_delay_ms=1.0, warmup_shape=(4,))
        try:
            assert handle.port != 0  # ephemeral port was bound
            health = _get(f"{handle.url}/healthz")
            assert health["ok"] and health["replicas"] == 2

            x = np.random.RandomState(0).rand(3, 4)
            out = _post(f"{handle.url}/predict",
                        {"inputs": x.tolist()})
            assert np.asarray(out["outputs"]).shape == (3, 3)
            assert len(out["classes"]) == 3
            ref = np.asarray(net.output(x.astype(np.float32)))
            np.testing.assert_allclose(np.asarray(out["outputs"]), ref,
                                       atol=1e-5)

            stats = _get(f"{handle.url}/stats")
            assert stats["replicas"]["rows"] >= 3
            assert stats["batcher"]["completed"] >= 1
            assert stats["batcher"]["queue_depth"] >= 0
            # per-bucket forward counts (3 rows -> the 8-bucket)
            assert sum(stats["replicas"]["bucket_forwards"].values()) >= 1
            assert stats["uptime_s"] >= 0
        finally:
            handle.close()

    def test_metrics_e2e_scrape(self):
        """Acceptance bar: a /metrics scrape on a live serve instance
        returns Prometheus text carrying train/serve/guardian/device
        series (docs/OBSERVABILITY.md)."""
        net = _net()
        with serve_network(net, n_replicas=1, max_batch_size=16,
                           max_delay_ms=1.0) as handle:
            x = np.random.RandomState(0).rand(2, 4)
            _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            with urllib.request.urlopen(f"{handle.url}/metrics",
                                        timeout=30) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            for series in (
                    "dl4j_serve_requests_total",      # serve
                    "dl4j_serve_latency_seconds_bucket",
                    "dl4j_serve_bucket_forwards_total",
                    "dl4j_batcher_queue_depth",
                    "dl4j_train_steps_total",         # train
                    "dl4j_guardian_events_total",     # guardian
                    "dl4j_device_count",              # device
                    "dl4j_device_memory_bytes",
                    "dl4j_jit_programs",
            ):
                assert series in text, f"{series} missing from /metrics"
            # this serve instance's engine actually counted the request
            assert 'dl4j_serve_requests_total{engine="' in text
            snap = _get(f"{handle.url}/snapshot")
            assert "dl4j_serve_requests" in snap
        # socket actually released: reconnect must fail fast
        with pytest.raises((ConnectionError, urllib.error.URLError, OSError)):
            _get(f"{handle.url}/healthz", timeout=2)
        # and the port is rebindable (server_close ran)
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", handle.port))

    def test_generate_endpoint(self):
        """Backward-compat: the legacy n_tokens request shape returns
        the same {"tokens": [[prompt+generated]]} rows — now served by
        the continuous-batching slot scheduler."""
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=4,
                           page_size=8) as handle:
            prompt = [[1, 2, 3, 4]]
            out = _post(f"{handle.url}/generate",
                        {"prompt": prompt, "n_tokens": 5})
            toks = np.asarray(out["tokens"])
            assert toks.shape == (1, 9)
            assert (toks[:, :4] == np.asarray(prompt)).all()
            assert ((0 <= toks) & (toks < CFG.vocab_size)).all()
            assert out["finish_reasons"] == ["max_tokens"]

    def test_generate_eos_and_per_request_max_tokens(self):
        """ISSUE satellite: per-request max_tokens + EOS-token early
        termination on /generate (ragged rows in one request)."""
        from deeplearning4j_tpu.serving.kv_cache import generate_cached
        import jax.numpy as jnp

        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        prompt = [1, 2, 3, 4]
        ref = np.asarray(generate_cached(
            params, jnp.asarray([prompt], jnp.int32), CFG, 12))[0, 4:]
        eos = int(ref[3])
        first = int(np.argmax(ref == eos))
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=4,
                           page_size=8) as handle:
            out = _post(f"{handle.url}/generate",
                        {"prompt": [prompt, [5, 6, 7]],
                         "max_tokens": 12, "eos_id": eos})
            # row 0 stopped at ITS eos; row 1 ran its own course
            assert out["tokens"][0] == prompt + ref[:first + 1].tolist()
            assert out["finish_reasons"][0] == "eos"
            assert out["finish_reasons"][1] in ("eos", "max_tokens")

    def test_generate_streaming_chunked(self):
        """ISSUE tentpole: streaming /generate — chunked transfer, one
        NDJSON line per token as slots emit, final summary line."""
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=4,
                           page_size=8) as handle:
            req = urllib.request.Request(
                f"{handle.url}/generate",
                data=json.dumps({"prompt": [[1, 2, 3, 4], [5, 6, 7]],
                                 "max_tokens": 6,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.headers["Content-Type"].startswith(
                    "application/x-ndjson")
                # tokens arrive line-by-line BEFORE the body ends
                events = []
                while True:
                    line = r.readline()
                    if not line:
                        break
                    events.append(json.loads(line))
            token_events = [e for e in events if "token" in e]
            final = events[-1]
            assert final["done"] is True
            assert len(token_events) == 12  # 6 per row
            # per-row order of streamed tokens == final row content
            for row in (0, 1):
                streamed = [e["token"] for e in token_events
                            if e["row"] == row]
                plen = len(final["tokens"][row]) - 6
                assert final["tokens"][row][plen:] == streamed
            # non-streaming twin returns the same rows (same greedy
            # decode through the same slot scheduler)
            out = _post(f"{handle.url}/generate",
                        {"prompt": [[1, 2, 3, 4], [5, 6, 7]],
                         "max_tokens": 6})
            assert out["tokens"] == final["tokens"]

    def test_decode_loop_metrics_e2e(self):
        """ISSUE satellite: dl4j_kv_pages_* / dl4j_decode_active_slots /
        streamed-token counters appear on a live /metrics scrape after
        /generate traffic."""
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=4,
                           page_size=8) as handle:
            _post(f"{handle.url}/generate",
                  {"prompt": [[1, 2, 3, 4]], "max_tokens": 5})
            with urllib.request.urlopen(f"{handle.url}/metrics",
                                        timeout=30) as r:
                text = r.read().decode()
            for series in (
                    "dl4j_kv_pages_total",
                    "dl4j_kv_pages_in_use",
                    "dl4j_decode_active_slots",
                    "dl4j_decode_tokens_streamed_total",
                    "dl4j_decode_requests_total",
                    "dl4j_decode_kv_read_bytes_total",
                    "dl4j_decode_step_seconds",
            ):
                assert series in text, f"{series} missing from /metrics"
            # the KV traffic counters carry both lane figures — the
            # streamed-kernel figure must undercut the dense one
            kv_read = {}
            for ln in text.splitlines():
                if ln.startswith("dl4j_decode_kv_read_bytes_total{"):
                    for path in ("kernel", "gather"):
                        if f'path="{path}"' in ln:
                            kv_read[path] = float(ln.split()[-1])
            assert kv_read.get("kernel", 0) > 0
            assert kv_read["gather"] > kv_read["kernel"]
            # the pool gauge reports this loop's configured size and
            # the request actually streamed its tokens
            label = gen.decode_loop.label
            assert (f'dl4j_kv_pages_total{{loop="{label}"}} '
                    f'{gen.decode_loop.n_pages}') in text
            streamed = [ln for ln in text.splitlines()
                        if ln.startswith("dl4j_decode_tokens_streamed")
                        and f'loop="{label}"' in ln]
            assert streamed and float(streamed[0].split()[-1]) >= 5
            # /stats carries the decode-loop occupancy surface
            stats = _get(f"{handle.url}/stats")
            dec = stats["generate"]["decode"]
            assert dec["pages_total"] == gen.decode_loop.n_pages
            assert dec["pages_in_use"] == 0  # request finished
            assert dec["decode_step_programs"] == 1

    def test_keepalive_connection_survives_early_reply_paths(self):
        """HTTP/1.1 keep-alive: a reply sent before the POST body was
        parsed (404 routes) must still consume the body, or the
        leftover bytes desync the connection for the next request."""
        import http.client

        with serve_network(_net(), n_replicas=1,
                           max_delay_ms=1.0) as handle:
            conn = http.client.HTTPConnection("127.0.0.1", handle.port,
                                              timeout=30)
            try:
                body = json.dumps({"prompt": [[1, 2]], "n_tokens": 2})
                # no generate engine -> 404 BEFORE the body is parsed
                conn.request("POST", "/generate", body=body,
                             headers={"Content-Type": "application/json"})
                assert conn.getresponse().read() is not None
                # unknown route with a body -> 404, body still drained
                conn.request("POST", "/nowhere", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 404
                resp.read()  # client must drain before reusing the conn
                # the SAME connection must still serve a real request
                x = np.random.RandomState(0).rand(2, 4)
                conn.request("POST", "/predict",
                             body=json.dumps({"inputs": x.tolist()}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                assert np.asarray(
                    json.loads(resp.read())["outputs"]).shape == (2, 3)
            finally:
                conn.close()

    def test_generate_slots_zero_selects_legacy_path(self):
        """slots=0 opts out of continuous batching: /generate serves
        the per-request compiled scan; stream/eos_id are rejected."""
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=0) as handle:
            assert gen.decode_loop is None
            out = _post(f"{handle.url}/generate",
                        {"prompt": [[1, 2, 3, 4]], "n_tokens": 5})
            assert len(out["tokens"][0]) == 9
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/generate",
                      {"prompt": [[1, 2]], "max_tokens": 2,
                       "stream": True})
            assert e.value.code == 400

    def test_generate_bad_row_does_not_orphan_row_mates(self):
        """All rows validate before any submits: a malformed row 400s
        the request and leaves no stream running in a slot."""
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=2,
                           page_size=8) as handle:
            overlong = list(range(CFG.max_len - 2))  # + max_tokens > max_len
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/generate",
                      {"prompt": [[1, 2, 3], overlong], "max_tokens": 8})
            assert e.value.code == 400
            snap = gen.decode_loop.snapshot()
            assert snap["occupied_slots"] == 0 and snap["queued"] == 0
            assert snap["requests"] == 0  # nothing was submitted

    def test_error_paths(self):
        with serve_network(_net(), n_replicas=1,
                           max_delay_ms=1.0) as handle:
            # bad JSON -> 400
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/predict", {"nope": 1})
            assert e.value.code == 400
            # feature-width mismatch surfaces as a request error
            with pytest.raises(urllib.error.HTTPError):
                _post(f"{handle.url}/predict",
                      {"inputs": [[1.0, 2.0]]})
            # /generate without a transformer engine -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/generate",
                      {"prompt": [[1]], "n_tokens": 2})
            assert e.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{handle.url}/nowhere")
            assert e.value.code == 404


class TestReadiness:
    """ISSUE 7 satellite: /healthz stays liveness; /readyz gates on
    warmup completion and decode-loop health."""

    def test_async_warmup_gates_readyz(self):
        import threading

        from deeplearning4j_tpu.serving import ReplicaSet

        net = _net()
        rs = ReplicaSet.for_network(net, n_replicas=1, max_batch_size=16)
        gate = threading.Event()
        inner_warmup = rs.warmup

        def gated_warmup(shape, **kw):
            assert gate.wait(30)
            inner_warmup(shape, **kw)

        rs.warmup = gated_warmup
        handle = serve_network(replicas=rs, max_delay_ms=1.0,
                               warmup_shape=(4,), warmup_async=True)
        try:
            # alive immediately, NOT ready until the warmup lands
            assert _get(f"{handle.url}/healthz")["ok"]
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{handle.url}/readyz")
            assert e.value.code == 503
            body = json.loads(e.value.read())
            assert body["ready"] is False
            assert "warmup" in body["reason"]
            gate.set()
            deadline = 30
            import time
            t0 = time.monotonic()
            while True:
                try:
                    ready = _get(f"{handle.url}/readyz")
                    break
                except urllib.error.HTTPError:
                    assert time.monotonic() - t0 < deadline
                    time.sleep(0.05)
            assert ready["ready"] and ready["warmup_done"]
            assert rs.engines[0].warmed_up
        finally:
            gate.set()
            handle.close()

    def test_sync_warmup_is_ready_from_first_connection(self):
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           warmup_shape=(4,)) as handle:
            assert _get(f"{handle.url}/readyz")["ready"] is True

    def test_dead_decode_loop_flips_readyz(self):
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=2,
                           page_size=8) as handle:
            assert _get(f"{handle.url}/readyz")["decode_loop_alive"]
            gen.decode_loop.close()  # the loop dies under the server
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(f"{handle.url}/readyz")
            assert e.value.code == 503
            body = json.loads(e.value.read())
            assert "decode loop" in body["reason"]
            # liveness is unaffected — the split is the point
            assert _get(f"{handle.url}/healthz")["ok"]


class TestOverloadShedding:
    """ISSUE 7 satellite: saturation answers 503 + Retry-After +
    {"error": "overloaded", "retry_after_ms": N} — machine-actionable
    end to end, on both /predict (batcher queue) and /generate
    (decode admission queue)."""

    def test_predict_queue_full_sheds_503_with_retry_after(self):
        import threading

        from deeplearning4j_tpu.serving import ReplicaSet

        gate = threading.Event()

        class GatedEngine:
            """Duck-typed engine: blocks until released."""

            decode_loop = None

            def infer(self, x):
                assert gate.wait(30)
                return np.zeros((x.shape[0], 3), np.float32)

            def snapshot(self):
                return {"requests": 0, "rows": 0, "errors": 0}

            def program_cache_size(self):
                return 0

        handle = serve_network(replicas=ReplicaSet([GatedEngine()]),
                               max_delay_ms=1.0, max_queue=1)
        try:
            results = []

            def post_bg():
                try:
                    results.append(_post(f"{handle.url}/predict",
                                         {"inputs": [[1.0, 2.0]]}))
                except Exception as e:  # noqa: BLE001
                    results.append(e)

            # request 1 occupies the engine; request 2 fills the queue
            threads = [threading.Thread(target=post_bg, daemon=True)
                       for _ in range(2)]
            threads[0].start()
            import time
            time.sleep(0.3)  # worker has dequeued req 1 into the engine
            threads[1].start()
            time.sleep(0.3)  # req 2 is parked in the queue
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/predict", {"inputs": [[1.0, 2.0]]})
            assert e.value.code == 503
            assert int(e.value.headers["Retry-After"]) >= 1
            body = json.loads(e.value.read())
            assert body["error"] == "overloaded"
            assert body["retry_after_ms"] > 0
            gate.set()
            for t in threads:
                t.join(timeout=30)
            assert all(isinstance(r, dict) for r in results)
            assert handle.batcher.snapshot()["shed"] == 1
        finally:
            gate.set()
            handle.close()

    def test_generate_admission_full_sheds_503(self):
        params = init_transformer_params(jax.random.PRNGKey(0), CFG)
        gen = InferenceEngine.for_transformer(params, CFG)
        with serve_network(_net(), n_replicas=1, max_delay_ms=1.0,
                           generate_engine=gen, slots=1, page_size=8,
                           max_waiting=0) as handle:
            assert gen.decode_loop.max_waiting == 0
            # request 1 occupies the single slot for ~max_len tokens;
            # reading its first streamed token proves it holds the slot
            req = urllib.request.Request(
                f"{handle.url}/generate",
                data=json.dumps({"prompt": [[1, 2, 3, 4]],
                                 "max_tokens": 48,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            r = urllib.request.urlopen(req, timeout=60)
            first = json.loads(r.readline())
            assert "token" in first
            # slot busy + max_waiting=0 -> the second request sheds
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/generate",
                      {"prompt": [[5, 6]], "max_tokens": 2})
            assert e.value.code == 503
            assert int(e.value.headers["Retry-After"]) >= 1
            body = json.loads(e.value.read())
            assert body["error"] == "overloaded"
            r.close()
            assert gen.decode_loop.snapshot()["shed"] == 1


class TestHotReload:
    """ISSUE satellite: POST /reload hot-swaps replica weights from a
    checkpoint path without dropping in-flight requests."""

    def _checkpoints(self, tmp_path):
        """Two nets with the same architecture but different weights,
        each checkpointed: (net_a, net_b, sharded_dir_b, npz_path_b)."""
        from deeplearning4j_tpu.checkpoint import ShardedModelSaver
        from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

        net_a, net_b = _net(), _net()
        x, y = (np.random.RandomState(1).rand(48, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[
                    np.random.RandomState(2).randint(0, 3, 48)])
        net_b.fit(x, y, epochs=3)  # diverge the weights
        sharded = str(tmp_path / "sharded")
        with ShardedModelSaver(sharded, sync=True) as saver:
            saver.save(net_b, iterator_position=3)
        npz = str(tmp_path / "b.ckpt")
        DefaultModelSaver(npz, keep_old=False).save(net_b)
        return net_a, net_b, sharded, npz

    def test_reload_swaps_weights_without_dropping_requests(self,
                                                            tmp_path):
        import threading

        net_a, net_b, sharded, _ = self._checkpoints(tmp_path)
        x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        ref_a = np.asarray(net_a.output(x))
        ref_b = np.asarray(net_b.output(x))
        assert not np.allclose(ref_a, ref_b)  # the swap is observable

        with serve_network(net_a, n_replicas=2, max_batch_size=16,
                           max_delay_ms=1.0, warmup_shape=(4,)) as handle:
            out = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["outputs"]), ref_a,
                                       atol=1e-5)

            # hammer /predict from the side WHILE reloading: every
            # response must be valid (old or new weights, never an error)
            stop = threading.Event()
            failures = []

            def hammer():
                while not stop.is_set():
                    try:
                        r = _post(f"{handle.url}/predict",
                                  {"inputs": x.tolist()})
                        got = np.asarray(r["outputs"])
                        if not (np.allclose(got, ref_a, atol=1e-5)
                                or np.allclose(got, ref_b, atol=1e-5)):
                            failures.append("torn outputs")
                    except Exception as e:  # noqa: BLE001
                        failures.append(repr(e))

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            try:
                res = _post(f"{handle.url}/reload", {"path": sharded})
            finally:
                stop.set()
                t.join(timeout=30)
            assert res["reloaded"] and res["replicas"] == 2
            assert res["step"] == 3
            assert failures == []

            # all replicas now serve net_b's weights
            out2 = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out2["outputs"]), ref_b,
                                       atol=1e-5)
            stats = _get(f"{handle.url}/stats")
            assert stats["last_reload"]["step"] == 3

    def test_reload_accepts_legacy_npz_checkpoints(self, tmp_path):
        net_a, net_b, _, npz = self._checkpoints(tmp_path)
        x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        ref_b = np.asarray(net_b.output(x))
        with serve_network(net_a, n_replicas=1,
                           max_delay_ms=1.0) as handle:
            _post(f"{handle.url}/reload", {"path": npz})
            out = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["outputs"]), ref_b,
                                       atol=1e-5)

    def test_reload_error_paths(self, tmp_path):
        net_a, _, sharded, npz = self._checkpoints(tmp_path)
        with serve_network(net_a, n_replicas=1,
                           max_delay_ms=1.0) as handle:
            # missing path key -> 400
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload", {})
            assert e.value.code == 400
            # nonexistent checkpoint -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload",
                      {"path": str(tmp_path / "nope")})
            assert e.value.code == 404
            # step pin against a single-file npz -> 400, not a silent
            # load of whatever the file holds
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload", {"path": npz, "step": 5})
            assert e.value.code == 400
            assert "no steps" in json.loads(e.value.read())["error"]
            # architecture mismatch -> 400 naming the leaf
            from deeplearning4j_tpu.checkpoint import ShardedModelSaver
            other_conf = (NeuralNetConfiguration.builder()
                          .lr(0.1).n_in(4).activation_function("tanh")
                          .optimization_algo("iteration_gradient_descent")
                          .num_iterations(1).use_adagrad(False)
                          .list(2).hidden_layer_sizes([16])
                          .override(1, layer="output",
                                    loss_function="mcxent",
                                    activation_function="softmax",
                                    n_out=3)
                          .pretrain(False).build())
            wide = MultiLayerNetwork(other_conf)
            wrong = str(tmp_path / "wrong")
            with ShardedModelSaver(wrong, sync=True) as saver:
                saver.save(wide)
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{handle.url}/reload", {"path": wrong})
            assert e.value.code == 400
            body = json.loads(e.value.read())
            assert "0/W" in body["error"]  # names the mismatched leaf
            # the serving weights are untouched after the failed reload
            x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
            out = _post(f"{handle.url}/predict", {"inputs": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["outputs"]),
                                       np.asarray(net_a.output(x)),
                                       atol=1e-5)


class TestCLIServe:
    def test_serve_smoke(self, tmp_path, capsys):
        from deeplearning4j_tpu.cli import main
        from deeplearning4j_tpu.scaleout.checkpoint import DefaultModelSaver

        ckpt = str(tmp_path / "m.ckpt")
        DefaultModelSaver(ckpt).save(_net())
        assert main(["serve", "-m", ckpt, "--replicas", "1",
                     "--max-delay-ms", "1", "--smoke"]) == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["serving"].startswith("http://127.0.0.1:")
        assert out["replicas"] == 1
