"""Distributed NLP performer tests (reference DistributedWord2VecTest /
DistributedGloveTest / WordCountTest, which run the full runtime with an
embedded tracker in one process — same tier here)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.huffman import build_huffman
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word2vec import WordVectors
from deeplearning4j_tpu.scaleout.api import CollectionJobIterator
from deeplearning4j_tpu.scaleout.perform_nlp import (
    NUM_WORDS_SO_FAR,
    DeltaAveragingAggregator,
    GloveWorkPerformer,
    Word2VecWorkPerformer,
    WordCountJobAggregator,
    WordCountWorkPerformer,
)
from deeplearning4j_tpu.scaleout.runtime import DistributedRuntime


def topic_sentences(n_reps=30):
    base = [
        "the cat sat on the mat",
        "the dog sat on the rug",
        "the cat and the dog play in the yard",
        "a furry cat chases a furry dog",
        "the king wears the crown in the castle",
        "the queen wears the crown in the castle",
        "a royal king and a royal queen sit on the throne",
    ]
    return base * n_reps


def built_vocab(sentences, min_freq=3.0):
    cache = build_vocab(sentences, DefaultTokenizerFactory(), min_freq)
    build_huffman(cache)
    return cache


class TestDistributedWord2Vec:
    def test_two_workers_learn_topic_structure(self):
        """DistributedWord2VecTest equivalent: sentence jobs fan out over
        the runtime, averaged deltas land on the current model."""
        sentences = topic_sentences()
        vocab = built_vocab(sentences)
        conf = {"vocab": vocab.to_dict(), "layer_size": 32, "window": 3,
                "negative": 0, "learning_rate": 0.1,
                "total_words": vocab.total_word_count * 4,
                "batch_pairs": 512, "seed": 7}
        # jobs = sentence batches, several passes (reference sentence jobs)
        batches = [sentences[i:i + 35]
                   for i in range(0, len(sentences), 35)] * 4

        seed_performer = Word2VecWorkPerformer()
        seed_performer.setup(conf)
        initial = seed_performer.pack()

        runtime = DistributedRuntime(
            CollectionJobIterator(batches),
            performer_factory=lambda: _fresh_performer(conf),
            n_workers=2,
            aggregator_factory=DeltaAveragingAggregator,
            initial_params=initial,
        )
        final = runtime.run(timeout=300.0)
        assert final is not None and final.shape == initial.shape
        # the words counter drove alpha decay
        assert runtime.tracker.count(NUM_WORDS_SO_FAR) > 0
        # install the final averaged tables and check embedding quality
        seed_performer.update(final)
        wv = seed_performer.word_vectors()
        assert wv.similarity("cat", "dog") > wv.similarity("cat", "king")

    def test_delta_results_not_full_tables(self):
        sentences = topic_sentences(5)
        vocab = built_vocab(sentences)
        conf = {"vocab": vocab.to_dict(), "layer_size": 16, "window": 3,
                "negative": 0, "learning_rate": 0.05,
                "total_words": vocab.total_word_count, "batch_pairs": 256,
                "seed": 1}
        performer = Word2VecWorkPerformer()
        performer.setup(conf)
        before = performer.pack()
        from deeplearning4j_tpu.scaleout.api import Job
        job = Job(work=sentences[:20], worker_id="w0")
        performer.perform(job)
        # result is the delta, so before + delta == after
        np.testing.assert_allclose(before + job.result, performer.pack(),
                                   atol=1e-5)
        assert np.abs(job.result).max() > 0  # training moved something


def _fresh_performer(conf):
    p = Word2VecWorkPerformer()
    p.setup(conf)
    return p


class TestDistributedGlove:
    def test_delta_training_reduces_loss(self):
        sentences = topic_sentences()
        vocab = built_vocab(sentences)
        from deeplearning4j_tpu.nlp.glove import CoOccurrences
        from deeplearning4j_tpu.nlp.sentence_iterator import (
            CollectionSentenceIterator)
        co = CoOccurrences(CollectionSentenceIterator(sentences),
                           DefaultTokenizerFactory(), vocab, window=3).calc()
        rows, cols, vals = co.triples()
        rng = np.random.RandomState(0)
        conf = {"vocab": vocab.to_dict(), "layer_size": 16,
                "learning_rate": 0.05, "seed": 3}

        def glove_jobs(n_jobs=12, size=256):
            out = []
            for _ in range(n_jobs):
                sel = rng.randint(0, rows.size, size)
                out.append({"rows": rows[sel], "cols": cols[sel],
                            "vals": vals[sel]})
            return out

        seed_perf = GloveWorkPerformer()
        seed_perf.setup(conf)
        initial = seed_perf.pack()

        def make():
            p = GloveWorkPerformer()
            p.setup(conf)
            return p

        runtime = DistributedRuntime(
            CollectionJobIterator(glove_jobs()),
            performer_factory=make, n_workers=2,
            aggregator_factory=DeltaAveragingAggregator,
            initial_params=initial)
        final = runtime.run(timeout=300.0)
        assert final is not None

        # weighted-LSQ loss of the averaged tables < initial tables
        def glove_loss(packed, perf):
            perf._install(packed)
            p = perf._params
            w = np.asarray(p["w"])[rows]
            c = np.asarray(p["c"])[cols]
            pred = ((w * c).sum(1) + np.asarray(p["bw"])[rows]
                    + np.asarray(p["bc"])[cols])
            err = pred - np.log(vals)
            fx = np.minimum(1.0, vals / 100.0) ** 0.75
            return float(0.5 * np.mean(fx * err * err))

        assert glove_loss(final, seed_perf) < glove_loss(initial, seed_perf)


class TestWordCount:
    def test_counter_merge_aggregation(self):
        """WordCountTest equivalent: per-job counts, Counter-merge."""
        sentences = ["the cat", "the dog", "a cat"]
        jobs = [[s] for s in sentences]
        runtime = DistributedRuntime(
            CollectionJobIterator(jobs),
            performer_factory=WordCountWorkPerformer,
            n_workers=2,
            aggregator_factory=WordCountJobAggregator)
        final = runtime.run(timeout=60.0)
        assert final == {"the": 2, "cat": 2, "dog": 1, "a": 1}
